"""SageMaker endpoint proxy.

Parity with reference: integrations/sagemaker/SagemakerProxy.py — a
SeldonComponent forwarding predict traffic to a SageMaker
invoke-endpoint. boto3 is optional (absent in this image); the runtime
client is injectable so the bridge is testable without AWS.

Parameters: ``endpoint_name``, ``region``, ``content_type``
(text/csv | application/json).
"""

from __future__ import annotations

import io
import json
import logging
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..user_model import SeldonComponent

logger = logging.getLogger(__name__)


class SageMakerServer(SeldonComponent):
    def __init__(
        self,
        model_uri: str = "",
        endpoint_name: str = "",
        region: str = "",
        content_type: str = "application/json",
        client_factory: Optional[Callable[[], Any]] = None,
        **kwargs,
    ):
        self.endpoint_name = endpoint_name or model_uri.rsplit("/", 1)[-1]
        if not self.endpoint_name:
            raise ValueError("sagemaker proxy needs endpoint_name (or modelUri)")
        self.region = region
        self.content_type = content_type
        self._client_factory = client_factory
        self._client = None

    def load(self) -> None:
        if self._client is not None:
            return
        if self._client_factory is not None:
            self._client = self._client_factory()
            return
        try:
            import boto3  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "SAGEMAKER_SERVER requires boto3 (absent in this image); "
                "inject client_factory for tests"
            ) from e
        self._client = boto3.client(
            "sagemaker-runtime", region_name=self.region or None
        )

    def _encode(self, arr: np.ndarray) -> bytes:
        if self.content_type == "text/csv":
            buf = io.StringIO()
            np.savetxt(buf, arr, delimiter=",", fmt="%g")
            return buf.getvalue().encode()
        return json.dumps({"instances": arr.tolist()}).encode()

    def _decode(self, body: bytes) -> np.ndarray:
        text = body.decode()
        if self.content_type == "text/csv":
            return np.loadtxt(io.StringIO(text), delimiter=",", ndmin=2)
        out = json.loads(text)
        if isinstance(out, dict):
            for key in ("predictions", "outputs"):
                if key in out:
                    out = out[key]
                    break
            else:
                raise RuntimeError(
                    f"unrecognized sagemaker response shape: keys {sorted(out)}"
                    " (expected 'predictions' or 'outputs')"
                )
        return np.asarray(out)

    def predict(self, X, names, meta=None):
        if self._client is None:
            self.load()
        arr = np.asarray(X)
        resp = self._client.invoke_endpoint(
            EndpointName=self.endpoint_name,
            ContentType=self.content_type,
            Accept=self.content_type,
            Body=self._encode(arr),
        )
        body = resp["Body"]
        raw = body.read() if hasattr(body, "read") else body
        return self._decode(raw if isinstance(raw, bytes) else raw.encode())

    def tags(self) -> Dict[str, Any]:
        return {"server": "sagemaker", "endpoint": self.endpoint_name}
