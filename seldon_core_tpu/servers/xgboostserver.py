"""XGBoost prepackaged server (import-gated; xgboost absent in this image).

Parity with reference: servers/xgboostserver/xgboostserver/XGBoostServer.py
(Booster loaded from model.bst).
"""

from __future__ import annotations

import os

import numpy as np

from ..storage import Storage
from ..user_model import SeldonComponent

BOOSTER_FILE = "model.bst"


class XGBoostServer(SeldonComponent):
    def __init__(self, model_uri: str, **kwargs):
        self.model_uri = model_uri
        self._booster = None

    def load(self) -> None:
        try:
            import xgboost as xgb
        except ImportError as e:
            raise RuntimeError(
                "XGBOOST_SERVER requires the xgboost package, not present in this image"
            ) from e
        model_dir = Storage.download(self.model_uri)
        self._booster = xgb.Booster(model_file=os.path.join(model_dir, BOOSTER_FILE))

    def predict(self, X, names, meta=None):
        import xgboost as xgb

        if self._booster is None:
            self.load()
        dmat = xgb.DMatrix(np.asarray(X))
        return self._booster.predict(dmat)
