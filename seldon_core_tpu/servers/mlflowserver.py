"""MLflow prepackaged server (import-gated; mlflow absent in this image).

Parity with reference: servers/mlflowserver/mlflowserver/MLFlowServer.py
(MLmodel-format pyfunc load).
"""

from __future__ import annotations

import numpy as np

from ..storage import Storage
from ..user_model import SeldonComponent


class MLFlowServer(SeldonComponent):
    def __init__(self, model_uri: str, **kwargs):
        self.model_uri = model_uri
        self._model = None

    def load(self) -> None:
        try:
            from mlflow import pyfunc
        except ImportError as e:
            raise RuntimeError(
                "MLFLOW_SERVER requires the mlflow package, not present in this image"
            ) from e
        model_dir = Storage.download(self.model_uri)
        self._model = pyfunc.load_model(model_dir)

    def predict(self, X, names, meta=None):
        if self._model is None:
            self.load()
        return np.asarray(self._model.predict(np.asarray(X)))
