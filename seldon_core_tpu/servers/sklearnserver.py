"""SKLearn prepackaged server.

Parity with reference: servers/sklearnserver/sklearnserver/SKLearnServer.py:15-43
(joblib-loaded model, ``method`` parameter selecting predict_proba vs
predict vs decision_function).
"""

from __future__ import annotations

import logging
import os

import numpy as np

from ..storage import Storage
from ..user_model import SeldonComponent

logger = logging.getLogger(__name__)

JOBLIB_FILE = "model.joblib"


class SKLearnServer(SeldonComponent):
    def __init__(self, model_uri: str, method: str = "predict_proba", **kwargs):
        self.model_uri = model_uri
        self.method = method
        self._model = None

    def load(self) -> None:
        import joblib

        model_dir = Storage.download(self.model_uri)
        path = os.path.join(model_dir, JOBLIB_FILE)
        if not os.path.exists(path):
            candidates = [f for f in os.listdir(model_dir) if f.endswith((".joblib", ".pkl"))]
            if not candidates:
                raise RuntimeError(f"no {JOBLIB_FILE} (or .pkl) under {self.model_uri}")
            path = os.path.join(model_dir, candidates[0])
        self._model = joblib.load(path)
        logger.info("sklearn model loaded from %s", path)

    def predict(self, X, names, meta=None):
        if self._model is None:
            self.load()
        arr = np.asarray(X)
        if self.method == "predict_proba" and hasattr(self._model, "predict_proba"):
            return self._model.predict_proba(arr)
        if self.method == "decision_function" and hasattr(self._model, "decision_function"):
            return self._model.decision_function(arr)
        return self._model.predict(arr)

    def class_names(self):
        if self._model is not None and hasattr(self._model, "classes_"):
            return [f"t:{c}" for c in self._model.classes_]
        return []
