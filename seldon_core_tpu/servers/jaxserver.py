"""JAX prepackaged server — the TPU-native flagship.

No reference counterpart by design: the reference served GPU/CPU models
via TFServing/Triton proxies (reference: integrations/tfserving/
TfServingProxy.py:21-60, integrations/nvidia-inference-server/TRTProxy.py);
this server runs models directly as jit-compiled XLA executables on TPU
(BASELINE.json north star: "add a servers/jaxserver prepackaged server").

Model URI layout::

    <model_uri>/jax_config.json   {"family": "resnet50"|"bert"|"llm"|"mlp",
                                   "config": {...model kwargs...},
                                   "checkpoint": "ckpt"}   # optional orbax dir
    <model_uri>/ckpt/             orbax checkpoint of params (optional; random
                                  init with config["seed"] when absent — used
                                  by benchmarks and tests)

Sharding: when constructed with a mesh (or ``tpu_mesh`` spec), params are
laid out by the model family's ``param_sharding`` rule and inputs by
``input_sharding`` — tensor parallelism over ICI, no code change in the
model. (reference's only analogue was K8s replica scaling.)
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import numpy as np

from ..storage import Storage
from ..user_model import JAXComponent

logger = logging.getLogger(__name__)


class JAXServer(JAXComponent):
    def __init__(self, model_uri: str, mesh=None, batch_size_hint: int = 8, **kwargs):
        super().__init__(mesh=mesh)
        self.model_uri = model_uri
        self.batch_size_hint = int(batch_size_hint)
        self._extra = kwargs
        self._family = None
        self._config: Dict[str, Any] = {}
        self._model = None

    # -- JAXComponent --

    def build(self):
        from .. import models as model_zoo

        model_dir = Storage.download(self.model_uri)
        cfg_path = os.path.join(model_dir, "jax_config.json")
        if not os.path.exists(cfg_path):
            raise RuntimeError(f"no jax_config.json under {self.model_uri}")
        with open(cfg_path) as f:
            cfg = json.load(f)
        self._family = cfg["family"]
        self._config = cfg.get("config", {})
        self._model = model_zoo.build(self._family, **self._config)
        params = None
        ckpt_rel = cfg.get("checkpoint")
        if ckpt_rel:
            ckpt_dir = os.path.join(model_dir, ckpt_rel)
            if os.path.isdir(ckpt_dir):
                params = self._restore_checkpoint(ckpt_dir)
        if params is None:
            seed = int(self._config.get("seed", 0))
            params = self._model.init_params(seed)
            logger.info("jaxserver %s: random-initialised params (seed=%d)", self._family, seed)
        return self._model.apply, params

    def _restore_checkpoint(self, ckpt_dir: str):
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(ckpt_dir)
        logger.info("jaxserver: restored checkpoint from %s", ckpt_dir)
        return restored

    def input_sharding(self, mesh):
        return self._model.input_sharding(mesh)

    def param_sharding(self, mesh, params):
        return self._model.param_sharding(mesh, params)

    @property
    def warmup_shape(self):
        return self._model.example_input_shape if self._model else None

    @warmup_shape.setter
    def warmup_shape(self, _v):  # JAXComponent sets it as a class attr default
        pass

    def class_names(self):
        names = self._config.get("class_names")
        return list(names) if names else []

    def tags(self):
        return {"family": self._family or "?", "server": "jaxserver"}
