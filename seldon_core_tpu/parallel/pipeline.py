"""GPipe pipeline parallelism inside shard_map.

Stages live on the ranks of the ``stage`` mesh axis; activations flow
stage->stage over a ``ppermute`` ring while microbatches stream in, giving
the classic (M + S - 1)-tick schedule. The scan body is uniform (every rank
computes every tick; injection/collection are masked by rank index) which
keeps it a single static XLA program — no data-dependent control flow.

Gradients flow backwards through the ppermute chain automatically (its
transpose is the reverse permutation), so ``jax.grad`` of a loss computed
from the pipeline output yields the standard GPipe backward schedule.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(
    stage_fn: Callable,  # (stage_params, x_mb) -> y_mb
    stage_params,  # rank-local params pytree for THIS stage
    x_mb,  # [M, mb, ...] microbatched input (used on stage 0)
    axis_name: str = "stage",
):
    """Run the pipeline; returns [M, mb, ...] outputs (valid on last stage,
    zeros elsewhere — mask downstream loss by stage)."""
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]

    from .vma import pvary

    perm = [(i, (i + 1) % S) for i in range(S)]
    # carries derive from x_mb (inheriting its varying axes) plus an
    # explicit pvary over the stage axis, which they acquire via ppermute
    state0 = pvary(x_mb[0] * 0, axis_name)
    outputs0 = pvary(x_mb * 0, axis_name)

    def tick(carry, t):
        state_prev, outputs = carry
        mb_idx = jnp.clip(t, 0, M - 1)
        inject = pvary(
            lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0, keepdims=False), axis_name
        )
        x_in = jnp.where(idx == 0, inject, state_prev)
        y = stage_fn(stage_params, x_in)
        out_idx = t - (S - 1)
        valid = (out_idx >= 0) & (idx == S - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype), jnp.clip(out_idx, 0, M - 1), axis=0
        )
        outputs = jnp.where(valid, updated, outputs)
        state_next = lax.ppermute(y, axis_name, perm)
        return (state_next, outputs), None

    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(M + S - 1))
    return outputs
