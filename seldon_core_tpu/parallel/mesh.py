"""Mesh construction helpers — single-chip through multi-host.

Multi-host model (SURVEY §5 "distributed communication backend"): the
reference's NCCL/MPI analogue is the JAX runtime itself — every host
runs the same program, ``initialize_distributed()`` wires the hosts into
one runtime (GCE metadata autodetect on TPU pods, explicit
coordinator/process env elsewhere), and ``jax.devices()`` then spans the
pod. Collectives ride ICI inside a slice and DCN between slices; the
mesh-building helpers put DCN-crossing axes (data, stage) on the outer
dimensions so tp/sp traffic never leaves a slice
(``make_hybrid_mesh``)."""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence


class MeshShapeError(ValueError):
    """A mesh shape that cannot be built or cannot shard the model.

    Raised by :func:`make_mesh` / :func:`parse_mesh_shape` /
    :func:`validate_model_dims` instead of letting XLA fail later with an
    opaque reshape/partition error. Subclasses ``ValueError`` so existing
    ``except ValueError`` admission paths keep refusing bad shapes."""


def factor_devices(n: int) -> Dict[str, int]:
    """Factor n devices into (data, stage, seq, model) prioritising: tp,
    then pp, then dp, then sp. All five strategies stay *wired* at any n
    (expert parallelism rides data x seq); axes degrade to 1 when chips run
    out. 8 chips -> {data:2, stage:2, seq:1, model:2}; 16 -> all 2;
    32 -> model 4.
    """
    if not isinstance(n, int) or n < 1:
        raise MeshShapeError(f"cannot factor {n!r} devices: need a positive int")
    axes = {"data": 1, "stage": 1, "seq": 1, "model": 1}
    order = ["model", "stage", "data", "seq"]
    i = 0
    while n > 1:
        axis = order[i % len(order)]
        if n % 2 == 0:
            axes[axis] *= 2
            n //= 2
        else:  # odd remainder goes to data
            axes["data"] *= n
            n = 1
        i += 1
    return axes


def make_mesh(shape: Dict[str, int], devices=None):
    """Build a Mesh with named axes from {axis: size}.

    Axis order follows the dict order; callers should put the slowest-
    varying (DCN-adjacent) axis first so ICI carries tp/sp collectives.
    """
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    total = 1
    for ax, s in shape.items():
        if not isinstance(s, int) or s < 1:
            raise MeshShapeError(
                f"mesh axis {ax!r}={s!r}: sizes must be positive ints"
            )
        total *= s
    if total > len(devices):
        raise MeshShapeError(
            f"mesh {shape} needs {total} devices, have {len(devices)}"
        )
    if len(devices) % total != 0:
        # a non-dividing shape would silently strand the remainder chips
        # outside the mesh while XLA still sees them via jax.devices() —
        # surface the mistake here with the arithmetic spelled out
        raise MeshShapeError(
            f"mesh {shape} covers {total} of {len(devices)} devices; "
            f"{total} does not divide {len(devices)} — the leftover "
            f"{len(devices) % total} chip(s) would idle"
        )
    arr = np.asarray(devices[:total]).reshape(tuple(shape.values()))
    return jax.sharding.Mesh(arr, tuple(shape.keys()))


def parse_mesh_shape(raw: str) -> Dict[str, int]:
    """Parse ``"data=2,model=4"`` into an ordered ``{axis: size}`` dict.

    Strict by design — this is the admission-time parser behind the
    ``seldon.io/mesh`` annotation and the ``mesh_shape`` server knob, so
    every malformed input gets a typed :class:`MeshShapeError` naming the
    offending fragment instead of an opaque downstream failure. Accepted
    axis names are the house mesh axes (data/stage/seq/model); duplicate
    axes and non-positive sizes are refused."""
    if not isinstance(raw, str) or not raw.strip():
        raise MeshShapeError(f"mesh shape {raw!r}: expected 'axis=N,axis=N'")
    allowed = ("data", "stage", "seq", "model")
    shape: Dict[str, int] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            raise MeshShapeError(f"mesh shape {raw!r}: empty segment")
        if "=" not in part:
            raise MeshShapeError(
                f"mesh shape segment {part!r}: expected 'axis=N'"
            )
        ax, _, val = part.partition("=")
        ax = ax.strip()
        if ax not in allowed:
            raise MeshShapeError(
                f"mesh axis {ax!r}: must be one of {allowed}"
            )
        if ax in shape:
            raise MeshShapeError(f"mesh axis {ax!r} given twice in {raw!r}")
        try:
            size = int(val.strip())
        except ValueError:
            raise MeshShapeError(
                f"mesh axis {ax!r}={val.strip()!r}: size must be an int"
            ) from None
        if size < 1:
            raise MeshShapeError(
                f"mesh axis {ax!r}={size}: sizes must be positive"
            )
        shape[ax] = size
    return shape


def validate_model_dims(
    shape: Dict[str, int],
    n_heads: int,
    d_ff: int,
    n_kv_heads: Optional[int] = None,
) -> None:
    """Reject a mesh whose ``model`` axis cannot shard the hard-split
    dims. Attention heads and the FFN hidden dim are partitioned (not
    replicated) under the TP layout, so ``model`` must divide both —
    otherwise XLA fails deep inside the first sharded dispatch with an
    unactionable partition error. KV heads are allowed to be indivisible
    (GQA targets / thin drafts): the cache layer replicates them instead,
    so that is NOT an error here."""
    tp = int(shape.get("model", 1))
    if tp <= 1:
        return
    if n_heads % tp != 0:
        raise MeshShapeError(
            f"mesh model={tp} does not divide n_heads={n_heads}; "
            "attention heads are hard-sharded over the model axis"
        )
    if d_ff % tp != 0:
        raise MeshShapeError(
            f"mesh model={tp} does not divide d_ff={d_ff}; "
            "the FFN hidden dim is hard-sharded over the model axis"
        )
    del n_kv_heads  # indivisible KV heads replicate — see cache_sharding


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this host into a multi-host JAX runtime.

    On TPU pods ``jax.distributed.initialize()`` autodetects everything
    from the metadata server; elsewhere pass the coordinator explicitly
    or set ``SELDON_TPU_COORDINATOR`` / ``SELDON_TPU_NUM_PROCESSES`` /
    ``SELDON_TPU_PROCESS_ID``. Idempotent: returns False when the
    runtime is already initialized or when running single-process with
    no coordinator configured (the common dev/test case).
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "SELDON_TPU_COORDINATOR"
    )
    if num_processes is None and "SELDON_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SELDON_TPU_NUM_PROCESSES"])
    if process_id is None and "SELDON_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SELDON_TPU_PROCESS_ID"])
    # decide the pod case from env alone — touching jax.default_backend()
    # here would initialize the XLA backends, after which
    # jax.distributed.initialize() refuses to run at all. A single-entry
    # TPU_WORKER_HOSTNAMES (e.g. "localhost" on a one-host slice) is not
    # a pod.
    workers = [
        w for w in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if w
    ]
    on_tpu_pod = len(workers) > 1 or bool(
        os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )
    if coordinator_address is None and not on_tpu_pod:
        return False
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg or "only be called once" in msg:
            # raced another initializer — the documented idempotent no-op
            return False
        if "must be called before" in msg:
            # distributed init was WANTED (coordinator/pod detected) but
            # something touched the XLA backends first: this host now runs
            # single-process and cross-host collectives will never form.
            # Loud warning instead of raise — serving a slice beats
            # crashing, but the operator must see it.
            import logging

            logging.getLogger(__name__).warning(
                "initialize_distributed: too late — XLA backends already "
                "initialized before the multi-host join (%s). This process "
                "continues SINGLE-HOST; call initialize_distributed() "
                "before any jax API use to form the pod.", e,
            )
            return False
        raise


def make_hybrid_mesh(
    ici_shape: Dict[str, int],
    dcn_shape: Optional[Dict[str, int]] = None,
    devices=None,
):
    """Mesh spanning slices/hosts: ``dcn_shape`` axes (typically data
    and/or stage — gradient/activation hops that tolerate DCN latency)
    partition BETWEEN slices, ``ici_shape`` axes (model/seq — latency-
    critical tp/sp collectives) partition WITHIN a slice.

    Falls back to a flat :func:`make_mesh` when there is a single slice
    (or no slice topology, e.g. the CPU test mesh) — same axis names, so
    callers never branch.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    dcn_shape = dict(dcn_shape or {})
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    dcn_total = 1
    for s in dcn_shape.values():
        dcn_total *= s
    if n_slices <= 1 or dcn_total <= 1:
        merged = {**dcn_shape, **ici_shape}
        for ax, size in dcn_shape.items():
            if ax in ici_shape:
                merged[ax] = ici_shape[ax] * size
        return make_mesh(merged, devices=devices)
    from jax.experimental import mesh_utils

    axis_names = list(dcn_shape.keys()) + [
        ax for ax in ici_shape if ax not in dcn_shape
    ]
    per_slice = [ici_shape.get(ax, 1) for ax in axis_names]
    across = [dcn_shape.get(ax, 1) for ax in axis_names]
    arr = mesh_utils.create_hybrid_device_mesh(
        per_slice, across, devices=devices
    )
    return jax.sharding.Mesh(arr, tuple(axis_names))
