"""Mesh construction helpers — single-chip through multi-host.

Multi-host model (SURVEY §5 "distributed communication backend"): the
reference's NCCL/MPI analogue is the JAX runtime itself — every host
runs the same program, ``initialize_distributed()`` wires the hosts into
one runtime (GCE metadata autodetect on TPU pods, explicit
coordinator/process env elsewhere), and ``jax.devices()`` then spans the
pod. Collectives ride ICI inside a slice and DCN between slices; the
mesh-building helpers put DCN-crossing axes (data, stage) on the outer
dimensions so tp/sp traffic never leaves a slice
(``make_hybrid_mesh``)."""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence


def factor_devices(n: int) -> Dict[str, int]:
    """Factor n devices into (data, stage, seq, model) prioritising: tp,
    then pp, then dp, then sp. All five strategies stay *wired* at any n
    (expert parallelism rides data x seq); axes degrade to 1 when chips run
    out. 8 chips -> {data:2, stage:2, seq:1, model:2}; 16 -> all 2;
    32 -> model 4.
    """
    axes = {"data": 1, "stage": 1, "seq": 1, "model": 1}
    order = ["model", "stage", "data", "seq"]
    i = 0
    while n > 1:
        axis = order[i % len(order)]
        if n % 2 == 0:
            axes[axis] *= 2
            n //= 2
        else:  # odd remainder goes to data
            axes["data"] *= n
            n = 1
        i += 1
    return axes


def make_mesh(shape: Dict[str, int], devices=None):
    """Build a Mesh with named axes from {axis: size}.

    Axis order follows the dict order; callers should put the slowest-
    varying (DCN-adjacent) axis first so ICI carries tp/sp collectives.
    """
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    total = 1
    for s in shape.values():
        total *= s
    if total > len(devices):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(tuple(shape.values()))
    return jax.sharding.Mesh(arr, tuple(shape.keys()))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this host into a multi-host JAX runtime.

    On TPU pods ``jax.distributed.initialize()`` autodetects everything
    from the metadata server; elsewhere pass the coordinator explicitly
    or set ``SELDON_TPU_COORDINATOR`` / ``SELDON_TPU_NUM_PROCESSES`` /
    ``SELDON_TPU_PROCESS_ID``. Idempotent: returns False when the
    runtime is already initialized or when running single-process with
    no coordinator configured (the common dev/test case).
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "SELDON_TPU_COORDINATOR"
    )
    if num_processes is None and "SELDON_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SELDON_TPU_NUM_PROCESSES"])
    if process_id is None and "SELDON_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SELDON_TPU_PROCESS_ID"])
    # decide the pod case from env alone — touching jax.default_backend()
    # here would initialize the XLA backends, after which
    # jax.distributed.initialize() refuses to run at all. A single-entry
    # TPU_WORKER_HOSTNAMES (e.g. "localhost" on a one-host slice) is not
    # a pod.
    workers = [
        w for w in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if w
    ]
    on_tpu_pod = len(workers) > 1 or bool(
        os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )
    if coordinator_address is None and not on_tpu_pod:
        return False
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg or "only be called once" in msg:
            # raced another initializer — the documented idempotent no-op
            return False
        if "must be called before" in msg:
            # distributed init was WANTED (coordinator/pod detected) but
            # something touched the XLA backends first: this host now runs
            # single-process and cross-host collectives will never form.
            # Loud warning instead of raise — serving a slice beats
            # crashing, but the operator must see it.
            import logging

            logging.getLogger(__name__).warning(
                "initialize_distributed: too late — XLA backends already "
                "initialized before the multi-host join (%s). This process "
                "continues SINGLE-HOST; call initialize_distributed() "
                "before any jax API use to form the pod.", e,
            )
            return False
        raise


def make_hybrid_mesh(
    ici_shape: Dict[str, int],
    dcn_shape: Optional[Dict[str, int]] = None,
    devices=None,
):
    """Mesh spanning slices/hosts: ``dcn_shape`` axes (typically data
    and/or stage — gradient/activation hops that tolerate DCN latency)
    partition BETWEEN slices, ``ici_shape`` axes (model/seq — latency-
    critical tp/sp collectives) partition WITHIN a slice.

    Falls back to a flat :func:`make_mesh` when there is a single slice
    (or no slice topology, e.g. the CPU test mesh) — same axis names, so
    callers never branch.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    dcn_shape = dict(dcn_shape or {})
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    dcn_total = 1
    for s in dcn_shape.values():
        dcn_total *= s
    if n_slices <= 1 or dcn_total <= 1:
        merged = {**dcn_shape, **ici_shape}
        for ax, size in dcn_shape.items():
            if ax in ici_shape:
                merged[ax] = ici_shape[ax] * size
        return make_mesh(merged, devices=devices)
    from jax.experimental import mesh_utils

    axis_names = list(dcn_shape.keys()) + [
        ax for ax in ici_shape if ax not in dcn_shape
    ]
    per_slice = [ici_shape.get(ax, 1) for ax in axis_names]
    across = [dcn_shape.get(ax, 1) for ax in axis_names]
    arr = mesh_utils.create_hybrid_device_mesh(
        per_slice, across, devices=devices
    )
    return jax.sharding.Mesh(arr, tuple(axis_names))
