"""Mesh construction helpers."""

from __future__ import annotations

from typing import Dict, Optional, Sequence


def factor_devices(n: int) -> Dict[str, int]:
    """Factor n devices into (data, stage, seq, model) prioritising: tp,
    then pp, then dp, then sp. All five strategies stay *wired* at any n
    (expert parallelism rides data x seq); axes degrade to 1 when chips run
    out. 8 chips -> {data:2, stage:2, seq:1, model:2}; 16 -> all 2;
    32 -> model 4.
    """
    axes = {"data": 1, "stage": 1, "seq": 1, "model": 1}
    order = ["model", "stage", "data", "seq"]
    i = 0
    while n > 1:
        axis = order[i % len(order)]
        if n % 2 == 0:
            axes[axis] *= 2
            n //= 2
        else:  # odd remainder goes to data
            axes["data"] *= n
            n = 1
        i += 1
    return axes


def make_mesh(shape: Dict[str, int], devices=None):
    """Build a Mesh with named axes from {axis: size}.

    Axis order follows the dict order; callers should put the slowest-
    varying (DCN-adjacent) axis first so ICI carries tp/sp collectives.
    """
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    total = 1
    for s in shape.values():
        total *= s
    if total > len(devices):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(tuple(shape.values()))
    return jax.sharding.Mesh(arr, tuple(shape.keys()))
