"""Mesh parallelism: dp / tp / pp / sp(ring attention) / ep over a Mesh.

The reference's only parallelism was request-level concurrency, K8s replica
scaling and traffic splitting (SURVEY.md §2: Spring @Async fan-out,
reference: engine/.../PredictiveUnitBean.java:169-180; HPA replicas,
reference: operator/controllers/seldondeployment_controller.go:87-109).
Model sharding did not exist. Here a single served/trained model spans the
chips of a slice, the scaling-book way: pick a mesh, annotate shardings or
write the collectives manually in shard_map, let ICI carry the traffic.

Axes (by convention):
  data  — batch (DP; gradients psum here)
  stage — pipeline stages (PP; ppermute activation ring)
  seq   — sequence chunks (SP; ring attention over ppermute)
  model — attention heads / FFN columns (TP; psum after row-parallel mats)
  expert parallelism rides the combined (data, seq) axes via all_to_all.
"""

from .mesh import (  # noqa: F401
    factor_devices,
    initialize_distributed,
    make_hybrid_mesh,
    make_mesh,
)
from .ring import ring_attention  # noqa: F401
