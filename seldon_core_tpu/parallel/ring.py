"""Ring attention: sequence-parallel exact attention over an ICI ring.

Long-context design (task requirement; the reference had no sequence axis
at all — SURVEY.md §5 "long-context: absent"). Each rank of the ``seq``
mesh axis holds one sequence chunk of Q, K, V. K/V chunks rotate around
the ring via ``lax.ppermute`` while each rank accumulates its Q-chunk's
attention with a numerically-stable online softmax (flash-attention style
running max/denominator), so peak memory stays O(T/n) per chip and the
DMA of the next chunk overlaps the matmul of the current one (XLA
schedules the ppermute async).

Causal masking works on global positions: rank r owns rows
[r*C, (r+1)*C); at ring step s it sees the K/V chunk originally owned by
rank (r - s) mod n, i.e. columns [(r-s)%n * C, ...). Blocks entirely in
the future are masked; XLA still executes them (static shapes) but a
`skip` factor zeroes their contribution.

Call INSIDE shard_map with the sequence axis name; degenerates to plain
attention when the axis has size 1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, bias, scale):
    """One (Q-chunk x K-chunk) block: returns (unnormalised out, row max,
    row denom) for online-softmax accumulation. q:[B,H,Tq,Dh] k/v:[B,H,Tk,Dh]"""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,Tq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Exact attention over a sequence-sharded ring.

    q, k, v: [B, H, C, Dh] local chunks (C = T / ring_size).
    Returns local [B, H, C, Dh] attention output.
    """
    from .vma import pvary

    ring = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    chunk = q.shape[2]
    scale = 1.0 / (q.shape[-1] ** 0.5)

    # inputs may arrive invariant over the ring axis (e.g. replicated
    # sequences); the rotating carries are varying by construction
    q, k, v = (pvary(t, axis_name) for t in (q, k, v))
    q32 = q.astype(jnp.float32)
    row_pos = rank * chunk + jnp.arange(chunk)  # global row ids [C]

    def step(carry, s):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src_rank = (rank - s) % ring  # owner of the visiting chunk
        col_pos = src_rank * chunk + jnp.arange(chunk)
        if causal:
            mask = row_pos[:, None] >= col_pos[None, :]  # [C, C]
            bias = jnp.where(mask, 0.0, -1e30)[None, None]
        else:
            bias = None
        o, m, l = _block_attn(q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32), bias, scale)
        # online softmax merge
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        o_acc = o_acc * alpha + o * beta
        l_acc = l_acc * alpha + l * beta
        # rotate K/V to the next rank (skip the final, unused rotation is
        # harmless and keeps the scan body uniform)
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, m_new, l_acc, k_nxt, v_nxt), None

    # initial accumulators derive from q so they inherit its full
    # varying-axes type (JAX >=0.9 tracks device-variance in avals); bare
    # jnp.zeros would be axis-invariant and fail the scan carry type check
    o0 = q32 * 0.0
    m0 = jnp.sum(o0, axis=-1, keepdims=True) - 1e30
    l0 = jnp.sum(o0, axis=-1, keepdims=True)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(ring))
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def full_attention(q, k, v, causal: bool = True, kv_len=None):
    """Single-chip reference attention (same signature minus the ring).
    ``kv_len`` (scalar, optional) masks key positions >= kv_len."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    t_q, t_k = q.shape[2], k.shape[2]
    if causal:
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    if kv_len is not None:
        s = jnp.where(jnp.arange(t_k)[None, None, None, :] < kv_len, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
