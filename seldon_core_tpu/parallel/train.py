"""Fully-parallel LM train step: dp x pp x sp x tp x ep in one shard_map.

The scaling-book recipe made explicit: one Mesh with axes
(data, stage, seq, model); parameters arrive pre-sharded (stage-stacked
blocks over ``stage``, head/FFN columns over ``model``, experts over the
combined (data, seq) ranks); the body is written rank-locally with manual
collectives — psum for tensor-parallel row-matmuls, ppermute rings for
both the GPipe stage loop and ring attention, all_to_all for expert
dispatch, and a final gradient psum over the replicated axes. ``jax.grad``
differentiates through every collective (their transposes are collectives
too), so the backward schedule falls out automatically.

No reference counterpart: SURVEY.md §2 records the reference's only
scaling axes as pod replicas and HTTP fan-out.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import numpy as np


def stack_stages(params: Dict[str, Any], n_stages: int) -> Dict[str, Any]:
    """Reshape block leaves [L, ...] -> [S, L/S, ...] for stage sharding."""
    import jax

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages:
            raise ValueError(f"n_layers {L} not divisible by {n_stages} stages")
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    out = dict(params)
    out["blocks"] = jax.tree_util.tree_map(reshape, params["blocks"])
    return out


def unstack_stages(params: Dict[str, Any]) -> Dict[str, Any]:
    import jax

    out = dict(params)
    out["blocks"] = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape(leaf.shape[0] * leaf.shape[1], *leaf.shape[2:]),
        params["blocks"],
    )
    return out


def param_specs(model, n_stages: int) -> Dict[str, Any]:
    """PartitionSpecs for stage-stacked params.

    blocks leaves are [S, L/S, ...]: dim0 -> stage; tensor-parallel dims ->
    model; the expert dim -> the combined (data, seq) ranks.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    col = {"wq", "wk", "wv", "w1", "w3"}  # last dim over model
    row = {"wo", "w2"}  # second-to-last dim over model

    def block_spec(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        nd = len(leaf.shape)
        if name in col:
            return P("stage", *([None] * (nd - 2)), "model")
        if name in row:
            return P("stage", *([None] * (nd - 3)), "model", None)
        if name in ("w1e", "w2e"):  # [S, Ls, E, D, F] / [S, Ls, E, F, D]
            return P("stage", None, ("data", "seq"), None, None)
        return P("stage", *([None] * (nd - 1)))  # ln1/ln2/router

    stacked = jax.eval_shape(lambda: stack_stages(model.init_params(0), n_stages))
    blocks = jax.tree_util.tree_map_with_path(block_spec, stacked["blocks"])
    from jax.sharding import PartitionSpec as P2

    return {
        "embed": P2(),
        "blocks": blocks,
        "ln_f": P2(),
        "unembed": P2(),
    }


def make_train_step(
    model,
    mesh,
    n_microbatches: int = 2,
    learning_rate: float = 1e-2,
    use_pipeline: Optional[bool] = None,
):
    """Build (init_sharded_params, train_step) for a mesh with axes
    (data, stage, seq, model).

    train_step(params, tokens) -> (params, loss).
    tokens: [B, T+1] int32, batch sharded over ``data``, REPLICATED over
    ``seq`` — each seq rank slices its own [T/sp]-chunk plus the next-token
    targets that spill across the chunk boundary. T must divide by sp;
    B by dp * n_microbatches.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = model.cfg
    S = mesh.shape.get("stage", 1)
    sp = mesh.shape.get("seq", 1)
    if use_pipeline is None:
        use_pipeline = S > 1
    specs = param_specs(model, S)

    from .pipeline import gpipe

    def local_loss(params, tokens):
        """Rank-local loss; global mean via psums. tokens [B_local, T+1]
        (full sequence; seq-replicated)."""
        dt = jnp.dtype(cfg.dtype)
        sp_rank = lax.axis_index("seq")
        T = tokens.shape[1] - 1
        T_local = T // sp
        start = sp_rank * T_local
        inputs = lax.dynamic_slice(tokens, (0, start), (tokens.shape[0], T_local))
        targets = lax.dynamic_slice(tokens, (0, start + 1), (tokens.shape[0], T_local))
        positions = start + jnp.arange(T_local)

        x = params["embed"][inputs].astype(dt)  # [B_local, T_local, D]

        # local stage shard arrives as [1, L/S, ...]; drop the unit dim
        blocks_local = jax.tree_util.tree_map(lambda l: l[0], params["blocks"])

        run_block = partial(
            model.backbone, tp_axis="model", sp_axis="seq", ep_axes=("data", "seq")
        )
        if use_pipeline:
            B_local = x.shape[0]
            M = n_microbatches
            mb = B_local // M
            x_mb = x.reshape(M, mb, T_local, -1)
            # KNOWN LIMIT: the MoE aux loss is dropped on the pipelined
            # path (the GPipe ring carries activations only; bubble ticks
            # would pollute a scalar side-channel). Router load-balancing
            # pressure therefore requires stage=1 or aux_loss_weight=0.
            y_mb = gpipe(
                lambda sp_params, xx: run_block(sp_params, xx, positions)[0],
                blocks_local,
                x_mb,
                "stage",
            )
            y = y_mb.reshape(B_local, T_local, -1)
            aux = jnp.float32(0.0)
        else:
            y, aux = run_block(blocks_local, x, positions)
            if cfg.n_experts > 0:
                # aux is a per-rank routing statistic; average over ep ranks
                aux = lax.pmean(aux, ("data", "seq"))

        from ..models.llm import _rms_norm

        y = _rms_norm(y, params["ln_f"].astype(dt), cfg.norm_eps)
        logits = (y @ params["unembed"].astype(dt)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss_local = jnp.sum(ce)
        count_local = jnp.float32(ce.size)
        loss_sum = lax.psum(loss_local, ("data", "seq"))
        count = lax.psum(count_local, ("data", "seq"))
        # only the last stage computed real logits (with S=1 every rank is
        # the last stage); zero the rest and share across stages — this
        # also discharges the stage-variance the stacked params introduced
        is_last = (lax.axis_index("stage") == S - 1).astype(jnp.float32)
        loss_sum = lax.psum(loss_sum * is_last, "stage")
        loss = loss_sum / count
        if not use_pipeline and cfg.n_experts > 0:
            # discharge aux's stage-variance the same way (S==1 here, so
            # the mask-psum is the identity on the value)
            aux = lax.psum(aux * is_last, "stage")
            loss = loss + cfg.aux_loss_weight * aux
        return loss

    def step_body(params, tokens):
        loss, grads = jax.value_and_grad(local_loss)(params, tokens)

        def sync(spec, g):
            # psum each grad over the axes it actually varies on MINUS the
            # axes its param is sharded over (those stay per-shard). The
            # vma type tracks the former exactly; relying on it (instead of
            # a hand-maintained table) keeps DP/TP/PP grad sync correct
            # even as the model wiring changes.
            sharded = set()
            for entry in spec:
                if entry is None:
                    continue
                sharded.update(entry if isinstance(entry, tuple) else (entry,))
            axes = tuple(a for a in jax.typeof(g).vma if a not in sharded)
            return lax.psum(g, axes) if axes else g

        grads = jax.tree_util.tree_map(sync, specs, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype), params, grads
        )
        return new_params, loss

    sharded_step = shard_map(
        step_body,
        mesh=mesh,
        in_specs=(specs, P("data", None)),
        out_specs=(specs, P()),
    )

    def to_named(tree_specs):
        return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), tree_specs)

    def init_sharded_params(seed: int = 0):
        host = model.init_params(seed)
        stacked = stack_stages(host, S)
        return jax.device_put(stacked, to_named(specs))

    train_step = jax.jit(
        sharded_step,
        in_shardings=(to_named(specs), NamedSharding(mesh, P("data", None))),
        out_shardings=(to_named(specs), NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    return init_sharded_params, train_step
