"""Mixture-of-Experts layer with expert parallelism (all_to_all over ICI).

Experts are sharded over the expert axis (by convention the combined
(data, seq) axes — expert parallelism reuses the data-parallel ranks, the
standard deployment). Dense dispatch/combine tensors keep everything
static-shaped for XLA: tokens route top-1 with a capacity buffer, overflow
drops (standard Switch-style routing).

Inside shard_map: x is the rank-local token slab; the two all_to_alls are
the only cross-chip traffic.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = Union[str, Sequence[str]]


def _axis_size(axis_names: AxisNames) -> int:
    if isinstance(axis_names, str):
        return lax.axis_size(axis_names)
    n = 1
    for a in axis_names:
        n *= lax.axis_size(a)
    return n


def moe_ffn(
    x,  # [N, D] rank-local tokens
    router_w,  # [D, E] replicated
    w1,  # [E_local, D, F] rank-local experts
    w2,  # [E_local, F, D]
    ep_axes: Optional[AxisNames],
    capacity_factor: float = 1.25,
):
    """Top-1 switch MoE. Returns ([N, D] outputs, aux load-balancing loss)."""
    N, D = x.shape
    E = router_w.shape[1]
    ep = _axis_size(ep_axes) if ep_axes else 1
    e_local = w1.shape[0]
    assert e_local * ep == E, f"experts {E} != {e_local} x ep {ep}"

    gate_logits = (x @ router_w).astype(jnp.float32)  # [N, E]
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]  # [N]

    # Switch aux loss: E * sum_e(fraction_tokens_e * mean_prob_e)
    one_hot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [N, E]
    density = one_hot.mean(0)
    density_proxy = probs.mean(0)
    aux_loss = E * jnp.sum(density * density_proxy)

    capacity = max(1, int(capacity_factor * N / E))
    # position of each token within its expert's buffer
    pos_in_expert = (jnp.cumsum(one_hot, axis=0) - 1.0) * one_hot  # [N, E]
    keep = (pos_in_expert < capacity) & (one_hot > 0)
    pos = jnp.sum(pos_in_expert * one_hot, axis=-1).astype(jnp.int32)  # [N]
    kept = jnp.any(keep, axis=-1)  # [N]

    # dispatch [N, E, C] one-hot; combine adds the gate weight
    dispatch = (
        jax.nn.one_hot(expert_idx, E, dtype=x.dtype)[:, :, None]
        * jax.nn.one_hot(pos, capacity, dtype=x.dtype)[:, None, :]
        * kept[:, None, None].astype(x.dtype)
    )
    combine = dispatch * gate.astype(x.dtype)[:, None, None]

    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)  # [E, C, D]
    if ep_axes:
        # [E, C, D] -> [ep, E_local, C, D]; trade the expert dim for the
        # rank dim so each rank ends with [E_local, ep*C, D]
        expert_in = expert_in.reshape(ep, e_local, capacity, D)
        # tiled=True concatenates received blocks along concat_axis in rank
        # order (tiled=False would insert a new axis at the wrong position)
        expert_in = lax.all_to_all(expert_in, ep_axes, split_axis=0, concat_axis=2, tiled=True)
        expert_in = expert_in.reshape(e_local, ep * capacity, D)
    h = jnp.einsum("ecd,edf->ecf", expert_in, w1)
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, w2)  # [E_local, ep*C, D]
    if ep_axes:
        expert_out = expert_out.reshape(e_local, ep, capacity, D)
        expert_out = lax.all_to_all(expert_out, ep_axes, split_axis=1, concat_axis=0, tiled=True)
        expert_out = expert_out.reshape(ep * e_local, capacity, D)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)
    return out, aux_loss
