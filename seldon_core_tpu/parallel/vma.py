"""Varying-manual-axes helpers (JAX >= 0.9 shard_map typing).

Inside shard_map, every value's aval carries the set of mesh axes it
varies over; scan carries and binary ops must agree on it. These helpers
smooth over the pvary -> pcast rename and let code promote values to a
target variance without hand-maintaining axis lists.
"""

from __future__ import annotations

from typing import Iterable, Tuple


def pvary(x, axes):
    """Promote x to vary over `axes` (only the ones it doesn't already)."""
    from jax import lax

    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    axes = tuple(a for a in axes if a not in vma_of(x))
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return lax.pvary(x, axes)


def vma_of(x) -> frozenset:
    import jax

    if hasattr(jax, "typeof"):
        aval = jax.typeof(x)
    else:  # jax < 0.6: no jax.typeof; core.get_aval is the same lookup
        aval = jax.core.get_aval(x)
    return getattr(aval, "vma", frozenset())


def tree_vma(tree) -> frozenset:
    import jax

    out: frozenset = frozenset()
    for leaf in jax.tree_util.tree_leaves(tree):
        out = out | vma_of(leaf)
    return out
