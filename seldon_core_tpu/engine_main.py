"""Engine CLI: boot a GraphExecutor from a predictor spec and serve.

Counterpart of the engine Spring Boot app (reference:
engine/src/main/java/io/seldon/engine/App.java:39-107): the graph comes
from the ``ENGINE_PREDICTOR`` env var (base64 JSON PredictorSpec —
reference: EnginePredictor.java:58-108) or a ``--spec`` JSON file; serves
external REST on :8000 and gRPC on :5001 (same defaults as the reference).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os

from .graph.service import EngineApp
from .graph.spec import PredictorSpec, default_predictor, validate_predictor


def main(argv=None) -> None:
    parser = argparse.ArgumentParser("seldon-tpu-engine")
    parser.add_argument("--spec", help="path to predictor spec JSON (else ENGINE_PREDICTOR b64 env)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=int(os.environ.get("ENGINE_SERVER_PORT", 8000)))
    parser.add_argument("--grpc-port", type=int, default=int(os.environ.get("ENGINE_SERVER_GRPC_PORT", 5001)))
    parser.add_argument("--no-grpc", action="store_true")
    parser.add_argument("--log-level", default=os.environ.get("SELDON_LOG_LEVEL", "INFO"))
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from .tracing import init_tracer

    init_tracer("seldon-tpu-engine")  # enabled iff TRACING env set

    if args.spec:
        with open(args.spec) as f:
            spec = PredictorSpec.from_dict(json.load(f))
    elif os.environ.get("ENGINE_PREDICTOR"):
        spec = PredictorSpec.from_env_b64(os.environ["ENGINE_PREDICTOR"])
    else:
        raise SystemExit("no graph: pass --spec or set ENGINE_PREDICTOR")
    spec = default_predictor(spec)
    validate_predictor(spec)

    from .graph.service import RequestLogger

    mesh = None
    if spec.tpu_mesh:
        # standalone engine process: the mesh spans this host's own devices
        from .parallel import make_mesh

        mesh = make_mesh(spec.tpu_mesh)
    app = EngineApp(spec, request_logger=RequestLogger.from_env(), mesh=mesh)
    try:
        asyncio.run(app.serve(args.host, args.http_port, None if args.no_grpc else args.grpc_port))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
