"""Minimal asyncio HTTP/1.1 server for the microservice and engine fronts.

The reference serves REST through Flask/gunicorn
(reference: python/seldon_core/microservice.py:153-264); this image has no
flask, and a hand-rolled asyncio loop with keep-alive beats WSGI on the
single-core hosts TPU VMs typically pair with anyway. Supports:
keep-alive, pipelining (sequential), Content-Length bodies, JSON and
form-encoded (``json=``) request bodies, and query-string ``?json=`` GETs
for reference-client compatibility
(reference: engine/.../service/InternalPredictionService.java:364-453 posts
form-encoded ``json=``).
"""

from __future__ import annotations

import asyncio
import json
import logging
import traceback
from typing import Awaitable, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

logger = logging.getLogger(__name__)

Handler = Callable[["Request"], Awaitable["Response"]]

_STATUS_TEXT = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    501: "Not Implemented",
    502: "Bad Gateway",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

# Request bodies are buffered in memory before dispatch, so an unbounded
# Content-Length is an OOM vector; the reference caps engine payloads the
# same way (InternalPredictionService.java:82-91 message-size annotations).
# Overridable per server via ``seldon.io/rest-max-body``.
DEFAULT_MAX_BODY_BYTES = 64 * 1024 * 1024


def max_body_from_env(default: int = DEFAULT_MAX_BODY_BYTES) -> int:
    """``SELDON_REST_MAX_BODY`` for servers with no predictor annotations
    (wrapper, gateway, request logger). Non-positive or junk values fall
    back to the default, matching the native engine's g_max_body_bytes."""
    import os

    try:
        v = int(os.environ["SELDON_REST_MAX_BODY"])
    except (KeyError, ValueError):
        return default
    return v if v > 0 else default


class Request:
    __slots__ = ("method", "path", "query", "headers", "body")

    def __init__(self, method: str, path: str, query: str, headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body

    def params(self) -> Dict[str, str]:
        """Query string as a flat dict (last value wins per key)."""
        if not self.query:
            return {}
        return {k: v[-1] for k, v in parse_qs(self.query).items()}

    def int_param(self, key: str) -> Optional[int]:
        """One integer query param, or None when absent/malformed."""
        try:
            return int(self.params()[key])
        except (KeyError, TypeError, ValueError):
            return None

    def json(self):
        """Decode the payload: JSON body, form-encoded ``json=``, query
        ``json=``, or multipart/form-data (reference: the engine accepts
        multipart predictions, RestClientController.java:136-206 — parts
        named after SeldonMessage fields: json, jsonData, strData,
        binData)."""
        ctype = self.headers.get("content-type", "")
        if self.body:
            if ctype.startswith("application/x-www-form-urlencoded"):
                form = parse_qs(self.body.decode("utf-8"))
                if "json" in form:
                    return json.loads(form["json"][0])
                raise ValueError("form body missing json field")
            if ctype.startswith("multipart/form-data"):
                return self._multipart_message(ctype)
            return json.loads(self.body)
        if self.query:
            q = parse_qs(self.query)
            if "json" in q:
                return json.loads(q["json"][0])
        return None

    def _multipart_message(self, ctype: str):
        import base64
        import re

        m = re.search(r'boundary="?([^";]+)"?', ctype)
        if not m:
            raise ValueError("multipart body missing boundary")
        delim = b"\r\n--" + m.group(1).encode()
        parts: Dict[str, bytes] = {}
        # a part's payload ends EXACTLY at the CRLF preceding the next
        # boundary — splitting on that delimiter keeps payloads byte-exact
        # (strip()-style trimming would eat a binData's own trailing \n).
        # Prepending CRLF makes the first boundary match the same pattern.
        for chunk in (b"\r\n" + self.body).split(delim)[1:]:
            if chunk.startswith(b"--"):
                break  # closing boundary
            if chunk.startswith(b"\r\n"):
                chunk = chunk[2:]
            head, sep, payload = chunk.partition(b"\r\n\r\n")
            if not sep:
                continue  # malformed part (no header/body separator)
            # require a preceding separator so `filename="..."` can never
            # satisfy the match when it appears before `name=` (RFC 7578
            # fixes no parameter order) — mirrors the native engine's parser
            nm = re.search(rb'(?:^|[;\s])name="([^"]+)"', head)
            if nm:
                parts[nm.group(1).decode("latin-1")] = payload
        if "json" in parts:  # a whole SeldonMessage as one part
            return json.loads(parts["json"])
        msg: Dict[str, object] = {}
        if "jsonData" in parts:
            msg["jsonData"] = json.loads(parts["jsonData"])
        elif "strData" in parts:
            msg["strData"] = parts["strData"].decode("utf-8")
        elif "binData" in parts:
            msg["binData"] = base64.b64encode(parts["binData"]).decode("ascii")
        elif "data" in parts:
            msg["data"] = json.loads(parts["data"])
        if not msg:
            raise ValueError(
                "multipart body has no json/jsonData/strData/binData/data part"
            )
        if "meta" in parts:
            msg["meta"] = json.loads(parts["meta"])
        return msg


def _json_default(obj):
    """bytes -> base64 string, the proto-JSON convention: interior message
    dicts may carry raw tensor bytes (payload.proto_to_json fast path)."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        import base64

        return base64.b64encode(bytes(obj)).decode("ascii")
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


class Response:
    __slots__ = ("status", "body", "content_type", "headers")

    def __init__(self, body, status: int = 200, content_type: str = "application/json",
                 headers: Optional[Dict[str, str]] = None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body, separators=(",", ":"), default=_json_default).encode()
        elif isinstance(body, str):
            body = body.encode()
        self.body = body or b""
        self.status = status
        self.content_type = content_type
        self.headers = headers

    def encode(self, keep_alive: bool) -> bytes:
        reason = _STATUS_TEXT.get(self.status, "Unknown")
        conn = "keep-alive" if keep_alive else "close"
        extra = ""
        if self.headers:
            extra = "".join(f"{k}: {v}\r\n" for k, v in self.headers.items())
        head = (
            f"HTTP/1.1 {self.status} {reason}\r\n"
            f"Content-Type: {self.content_type}\r\n"
            f"Content-Length: {len(self.body)}\r\n"
            f"{extra}"
            f"Connection: {conn}\r\n\r\n"
        )
        return head.encode() + self.body


class StreamingResponse:
    """Chunked-transfer response driven by a (possibly blocking) iterator
    of byte chunks — the server pulls items on the default executor so a
    queue-backed generator (SSE token streaming) never blocks the event
    loop. The connection closes after the stream (simplest correct
    keep-alive story for a body of unknown length)."""

    __slots__ = ("iterator", "status", "content_type", "on_abort")

    def __init__(self, iterator, status: int = 200,
                 content_type: str = "text/event-stream", on_abort=None):
        self.iterator = iterator
        self.status = status
        self.content_type = content_type
        # called when the client goes away mid-stream (disconnect): gives
        # the producer a chance to cancel upstream work so the iterator
        # can finish (and its finally blocks run) instead of lingering
        self.on_abort = on_abort

    def head(self) -> bytes:
        reason = _STATUS_TEXT.get(self.status, "Unknown")
        return (
            f"HTTP/1.1 {self.status} {reason}\r\n"
            f"Content-Type: {self.content_type}\r\n"
            f"Transfer-Encoding: chunked\r\n"
            f"Connection: close\r\n\r\n"
        ).encode()


class HTTPServer:
    """Exact-path router + asyncio serve loop."""

    def __init__(
        self,
        name: str = "http",
        max_body_bytes: Optional[int] = DEFAULT_MAX_BODY_BYTES,
        read_timeout_s: Optional[float] = None,
    ):
        self.name = name
        self.routes: Dict[str, Handler] = {}
        self.prefix_routes: Dict[str, Handler] = {}
        self.max_body_bytes = max_body_bytes
        # slowloris guard: cap the wall-clock wait for a request's bytes
        # once the first header byte could have arrived
        self.read_timeout_s = read_timeout_s
        # optional admission hook, called with (method, path, headers) BEFORE
        # the body is read: returning a Response answers immediately and the
        # body is chunk-discarded unparsed. An overloaded server must shed
        # load from the headers — receiving + parsing a few-hundred-KB body
        # per rejected retry turns the 429 path itself into the bottleneck.
        self.early_gate: Optional[Any] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def route(self, path: str):
        def deco(fn: Handler) -> Handler:
            self.routes[path] = fn
            return fn

        return deco

    def add_route(self, path: str, fn: Handler) -> None:
        self.routes[path] = fn

    def add_prefix_route(self, prefix: str, fn: Handler) -> None:
        """Route every path under `prefix` (longest prefix wins)."""
        self.prefix_routes[prefix] = fn

    async def _dispatch(self, req: Request) -> Response:
        handler = self.routes.get(req.path)
        if handler is None and self.prefix_routes:
            for prefix in sorted(self.prefix_routes, key=len, reverse=True):
                if req.path.startswith(prefix):
                    handler = self.prefix_routes[prefix]
                    break
        if handler is None:
            return Response({"status": {"info": f"no route {req.path}", "code": 404, "status": "FAILURE"}}, 404)
        try:
            return await handler(req)
        except (ValueError, KeyError) as e:
            return Response(error_body(400, str(e)), 400)
        except Exception as e:  # surface the traceback for debuggability
            logger.error("handler %s failed: %s\n%s", req.path, e, traceback.format_exc())
            return Response(error_body(500, f"{type(e).__name__}: {e}"), 500)

    async def _bail(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, resp: Response):
        """Terminal error response on a connection that will close with
        request bytes possibly still inbound (oversized/stalled body).
        Flush the response, then absorb a bounded amount of the unread
        body — closing with unread data in the kernel buffer RSTs the
        socket and can destroy the response before the client reads it."""
        writer.write(resp.encode(False))
        try:
            await writer.drain()
            loop = asyncio.get_running_loop()
            # wall-clock-bounded (not byte-capped) drain: chunks are
            # discarded so memory is constant, the deadline bounds CPU,
            # and a byte cap would reintroduce the RST for any fast
            # sender past it (a real 64MB upload clears in well under 1s
            # on loopback/datacenter links)
            deadline = loop.time() + 1.0
            while loop.time() < deadline:
                chunk = await asyncio.wait_for(reader.read(65536), 0.5)
                if not chunk:
                    break
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                try:
                    if self.read_timeout_s:
                        # slowloris guard doubling as the keep-alive idle
                        # reaper: a connection that can't produce a full
                        # header block in time is closed (silently — an
                        # idle keep-alive conn isn't an error)
                        header_blob = await asyncio.wait_for(
                            reader.readuntil(b"\r\n\r\n"), self.read_timeout_s
                        )
                    else:
                        header_blob = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionResetError):
                    break
                except asyncio.LimitOverrunError:
                    await self._bail(reader, writer, Response(error_body(400, "headers too large"), 400))
                    break
                lines = header_blob.decode("latin-1").split("\r\n")
                try:
                    method, target, _version = lines[0].split(" ", 2)
                except ValueError:
                    await self._bail(reader, writer, Response(error_body(400, "bad request line"), 400))
                    break
                headers: Dict[str, str] = {}
                for line in lines[1:]:
                    if not line:
                        continue
                    k, _, v = line.partition(":")
                    headers[k.strip().lower()] = v.strip()
                try:
                    length = int(headers.get("content-length", 0))
                except ValueError:
                    length = -1
                if length < 0:
                    await self._bail(reader, writer, Response(error_body(400, "bad Content-Length"), 400))
                    break
                if self.max_body_bytes is not None and length > self.max_body_bytes:
                    # reject before reading: never buffer an oversized body
                    await self._bail(
                        reader,
                        writer,
                        Response(
                            error_body(
                                413,
                                f"body {length} bytes exceeds limit "
                                f"{self.max_body_bytes}",
                            ),
                            413,
                        ),
                    )
                    break
                if self.early_gate is not None:
                    parts0 = urlsplit(target)
                    gate_resp = self.early_gate(
                        method, unquote(parts0.path), headers
                    )
                    if gate_resp is not None:
                        keep = headers.get("connection", "keep-alive").lower() != "close"
                        try:
                            remaining = length
                            # discard, never buffer — under the same
                            # slowloris guard as the real body read (a
                            # trickled body must not hold the fd open)
                            deadline = (
                                asyncio.get_running_loop().time()
                                + (self.read_timeout_s or 30.0)
                            )
                            while remaining > 0:
                                budget = deadline - asyncio.get_running_loop().time()
                                if budget <= 0:
                                    keep = False
                                    break
                                chunk = await asyncio.wait_for(
                                    reader.read(min(65536, remaining)), budget
                                )
                                if not chunk:
                                    keep = False
                                    break
                                remaining -= len(chunk)
                            writer.write(gate_resp.encode(keep))
                            await writer.drain()
                        except (asyncio.TimeoutError, ConnectionError, OSError):
                            break
                        if not keep:
                            break
                        continue
                try:
                    if length and self.read_timeout_s:
                        body = await asyncio.wait_for(
                            reader.readexactly(length), self.read_timeout_s
                        )
                    else:
                        body = await reader.readexactly(length) if length else b""
                except asyncio.TimeoutError:
                    await self._bail(
                        reader, writer, Response(error_body(408, "body read timed out"), 408)
                    )
                    break
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                parts = urlsplit(target)
                req = Request(method, unquote(parts.path), parts.query, headers, body)
                keep = headers.get("connection", "keep-alive").lower() != "close"
                resp = await self._dispatch(req)
                if isinstance(resp, StreamingResponse):
                    loop = asyncio.get_running_loop()
                    it = iter(resp.iterator)
                    sentinel = object()
                    try:
                        writer.write(resp.head())
                        await writer.drain()
                        while True:
                            chunk = await loop.run_in_executor(None, next, it, sentinel)
                            if chunk is sentinel:
                                break
                            if not chunk:
                                continue
                            writer.write(
                                f"{len(chunk):x}\r\n".encode() + bytes(chunk) + b"\r\n"
                            )
                            await writer.drain()
                        writer.write(b"0\r\n\r\n")
                        await writer.drain()
                    except (ConnectionError, OSError, asyncio.CancelledError):
                        # client went away mid-stream: cancel upstream work,
                        # then drain the iterator on the executor so its
                        # finally blocks (in-flight gauges, lane release)
                        # run promptly instead of at GC time
                        if resp.on_abort is not None:
                            try:
                                resp.on_abort()
                            except Exception:  # noqa: BLE001
                                logger.exception("stream abort hook failed")

                        def _drain(iterator=it):
                            # BaseException: a cancelled request surfaces
                            # concurrent.futures.CancelledError (a
                            # BaseException since 3.8) from the iterator
                            try:
                                for _ in iterator:
                                    pass
                            except BaseException:  # noqa: BLE001
                                pass
                            try:
                                iterator.close()
                            except BaseException:  # noqa: BLE001
                                pass

                        loop.run_in_executor(None, _drain)
                    break  # Connection: close after a chunked stream
                writer.write(resp.encode(keep))
                await writer.drain()
                if not keep:
                    break
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def start(self, host: str, port: int, reuse_port: bool = False):
        # reuse_port: multiple worker processes share one listening port
        # (the kernel load-balances accepts — the no-fork multi-worker model)
        self._server = await asyncio.start_server(
            self._handle_conn, host, port, limit=64 * 1024 * 1024,
            reuse_port=reuse_port or None,
        )
        logger.info("%s listening on %s:%d", self.name, host, port)
        return self._server

    async def serve_forever(self, host: str, port: int, reuse_port: bool = False):
        await self.start(host, port, reuse_port=reuse_port)
        await self.serve()

    async def serve(self):
        """Serve on an already-``start()``-ed listener. Callers that must
        guarantee the socket is bound before advertising readiness (the
        component runtime) await ``start()`` first, then run this in a
        task."""
        # no `async with`: its __aexit__ AWAITS wait_closed(), which blows
        # up with "coroutine ignored GeneratorExit" when the coroutine is
        # garbage-collected mid-suspend (event loop stopped under it) —
        # the synchronous close() is all the cleanup needed
        try:
            await self._server.serve_forever()
        finally:
            self._server.close()

    def is_serving(self) -> bool:
        return self._server is not None and self._server.is_serving()

    def close(self):
        if self._server is not None:
            self._server.close()


def error_body(code: int, info: str) -> dict:
    return {"status": {"code": code, "info": info, "status": "FAILURE"}}
