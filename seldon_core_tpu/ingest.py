"""Async ingestion tier: broker-fed scoring with at-least-once delivery.

Counterpart of the reference's Kafka request plane (reference:
kafka/kafka.json:1-25 — a Kafka+Zookeeper cluster manifest; helm chart
``seldon-core-kafka``): records are produced into a durable queue and a
consumer drains them through the engine asynchronously, decoupling
producers from serving capacity. Redesigned rather than ported:

* **Durable queue** = append-only JSONL segment files + an fsync'd commit
  file per consumer group (``FileQueue``). No broker process to operate;
  the same ``Broker`` protocol admits a real Kafka client where one
  exists (``KafkaBroker`` is import-gated).
* **At-least-once**: the consumer commits offsets only after the engine
  call (or its dead-lettering) completes, and only CONTIGUOUSLY — a
  crash between scoring and commit replays the tail. The results sink is
  keyed by record id, so replays overwrite identically: exactly-once
  *observable* despite at-least-once delivery.
* **Dead-letter path**: a record that still fails after ``retries``
  engine calls is appended to ``dead_letter.jsonl`` with the error and
  counts as handled (the queue never wedges on a poison record).
* **Backpressure**: bounded in-flight concurrency; the consumer polls
  only while slots are free, so a slow engine slows the drain instead of
  ballooning memory. Batched with the engine's micro-batcher, queue
  records fuse into full device launches — the TPU-side win of an ingest
  tier (arrival jitter is absorbed by the queue, not the batcher timer).

CLI::

    python -m seldon_core_tpu.ingest enqueue --queue-dir q --file recs.jsonl
    python -m seldon_core_tpu.ingest consume --queue-dir q \
        --engine 127.0.0.1:8000 --group g1 --out results.jsonl
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

SEGMENT_MAX_RECORDS = 4096


class Broker:
    """Minimal consumer-side broker contract (Kafka-shaped): poll records
    from an offset, commit an offset for a group."""

    def append(self, record: Dict[str, Any]) -> int:
        raise NotImplementedError

    def poll(self, offset: int, max_records: int) -> List[Tuple[int, Dict[str, Any]]]:
        raise NotImplementedError

    def committed(self, group: str) -> int:
        raise NotImplementedError

    def commit(self, group: str, offset: int) -> None:
        raise NotImplementedError


class FileQueue(Broker):
    """Append-only JSONL segments + per-group commit files.

    Offsets are global record indices; segment files are named by their
    base offset (``segment-<base>.jsonl``). Appends fsync the segment;
    commits write-then-rename an offset file (crash-atomic)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- producer -----------------------------------------------------------

    def _segments(self) -> List[int]:
        bases = []
        for f in os.listdir(self.root):
            if f.startswith("segment-") and f.endswith(".jsonl"):
                bases.append(int(f[len("segment-"):-len(".jsonl")]))
        return sorted(bases)

    def _segment_path(self, base: int) -> str:
        return os.path.join(self.root, f"segment-{base:012d}.jsonl")

    def _count(self, base: int) -> int:
        try:
            with open(self._segment_path(base), "rb") as f:
                return sum(1 for _ in f)
        except FileNotFoundError:
            return 0

    def end_offset(self) -> int:
        bases = self._segments()
        if not bases:
            return 0
        return bases[-1] + self._count(bases[-1])

    def append(self, record: Dict[str, Any]) -> int:
        bases = self._segments()
        if not bases:
            base, n = 0, 0
        else:
            base = bases[-1]
            n = self._count(base)
            if n >= SEGMENT_MAX_RECORDS:
                base, n = base + n, 0
        off = base + n
        with open(self._segment_path(base), "a", encoding="utf-8") as f:
            f.write(json.dumps(record, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return off

    def append_many(self, records: List[Dict[str, Any]]) -> int:
        """Batched append, ONE fsync per touched segment; returns the first
        offset. Rotates at SEGMENT_MAX_RECORDS exactly like append() — a
        bulk enqueue must not produce one unbounded segment (poll() scans
        a segment from its base, so oversized segments make the drain
        quadratic)."""
        if not records:
            return self.end_offset()
        bases = self._segments()
        base = bases[-1] if bases else 0
        n = self._count(base) if bases else 0
        first = base + n
        i = 0
        while i < len(records):
            if n >= SEGMENT_MAX_RECORDS:
                base, n = base + n, 0
            take = records[i:i + (SEGMENT_MAX_RECORDS - n)]
            with open(self._segment_path(base), "a", encoding="utf-8") as f:
                for r in take:
                    f.write(json.dumps(r, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            n += len(take)
            i += len(take)
        return int(first)

    # -- consumer -----------------------------------------------------------

    def poll(self, offset: int, max_records: int) -> List[Tuple[int, Dict[str, Any]]]:
        out: List[Tuple[int, Dict[str, Any]]] = []
        for base in self._segments():
            if out and len(out) >= max_records:
                break
            count = self._count(base)
            if base + count <= offset:
                continue
            with open(self._segment_path(base), encoding="utf-8") as f:
                for i, line in enumerate(f):
                    off = base + i
                    if off < offset:
                        continue
                    if len(out) >= max_records:
                        break
                    try:
                        out.append((off, json.loads(line)))
                    except json.JSONDecodeError:
                        # torn final line of a crashed producer: stop here,
                        # the record was never fully appended
                        return out
        return out

    def _commit_path(self, group: str) -> str:
        return os.path.join(self.root, f"commit-{group}.json")

    def committed(self, group: str) -> int:
        try:
            with open(self._commit_path(group)) as f:
                return int(json.load(f)["offset"])
        except (FileNotFoundError, ValueError, KeyError):
            return 0

    def commit(self, group: str, offset: int) -> None:
        tmp = self._commit_path(group) + ".tmp"
        with open(tmp, "w") as f:
            # persisted absolute stamp read by humans across process
            # lifetimes — monotonic would be meaningless on disk
            # seldon-lint: disable=wall-clock
            json.dump({"offset": int(offset), "ts": time.time()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._commit_path(group))


class KafkaBroker(Broker):
    """Adapter for a real Kafka cluster (the reference's deployment mode,
    kafka/kafka.json:1-25 + helm-charts/seldon-core-kafka): the ``Broker``
    contract over confluent-kafka's Producer/Consumer API against a
    single-partition topic (partition 0 — contiguous offsets, matching
    FileQueue's total order; scale-out shards by running one consumer per
    topic, not by partitions).

    Import-gated optional dependency: constructing without
    ``confluent_kafka`` installed (and without injected client classes)
    raises ImportError. The client classes are injectable so the contract
    tests run the SAME suite as FileQueue against a stub cluster
    (tests/test_kafka_broker.py) — the adapter code paths exercised there
    are exactly the deployable ones.

    Deploy wiring::

        python -m seldon_core_tpu.ingest consume \\
            --kafka broker-0.kafka:9092 --topic seldon-requests \\
            --engine engine.default.svc:8000 --group scorer --out r.jsonl
    """

    def __init__(self, topic: str, bootstrap: str = "localhost:9092",
                 producer_cls=None, consumer_cls=None, tp_cls=None,
                 poll_timeout_s: float = 1.0):
        if producer_cls is None or consumer_cls is None or tp_cls is None:
            try:
                import confluent_kafka  # type: ignore
            except ImportError as e:  # pragma: no cover - no client in image
                raise ImportError(
                    "confluent_kafka is not available in this image; use "
                    "FileQueue or run the consumer next to a broker with "
                    "the client installed"
                ) from e
            producer_cls = confluent_kafka.Producer      # pragma: no cover
            consumer_cls = confluent_kafka.Consumer      # pragma: no cover
            tp_cls = confluent_kafka.TopicPartition      # pragma: no cover
        self.topic = topic
        self.bootstrap = bootstrap
        self.poll_timeout_s = poll_timeout_s
        self._tp = tp_cls
        self._consumer_cls = consumer_cls
        self._producer = producer_cls({"bootstrap.servers": bootstrap})
        self._reader = None           # offset-addressed poll() consumer
        self._reader_next = None      # offset the reader is positioned at
        self._group_consumers: Dict[str, Any] = {}

    # -- producer side ------------------------------------------------------

    def append(self, record: Dict[str, Any]) -> int:
        return self.append_many([record])

    def append_many(self, records: List[Dict[str, Any]]) -> int:
        """Produce the whole batch, then ONE flush — the durability
        barrier FileQueue gets from fsync, without paying a broker
        round-trip per record. Returns the FIRST offset of the batch
        (FileQueue's contract)."""
        if not records:
            # FileQueue parity: an empty batch returns the end offset
            # (the offset the next record would get) via the broker's
            # high watermark
            c = self._group_consumer("__seldon_tpu_watermark__")
            _lo, hi = c.get_watermark_offsets(self._tp(self.topic, 0))
            return int(hi)
        delivered: List[int] = []
        errors: List[Any] = []

        def on_delivery(err, msg):
            if err is not None:
                errors.append(err)
            else:
                delivered.append(msg.offset())

        for record in records:
            self._producer.produce(
                self.topic,
                json.dumps(record, separators=(",", ":")).encode("utf-8"),
                on_delivery=on_delivery,
            )
        self._producer.flush()
        if errors:
            raise KafkaIngestError(f"produce failed: {errors[0]}")
        if len(delivered) != len(records):
            raise KafkaIngestError(
                f"only {len(delivered)}/{len(records)} produces acknowledged"
            )
        return min(delivered)

    # -- consumer side ------------------------------------------------------

    def poll(self, offset: int, max_records: int
             ) -> List[Tuple[int, Dict[str, Any]]]:
        if max_records <= 0:
            return []
        if self._reader is None:
            self._reader = self._consumer_cls({
                "bootstrap.servers": self.bootstrap,
                # offset-addressed reads: this consumer NEVER commits; the
                # group consumers own commit state
                "group.id": "__seldon_tpu_reader__",
                "enable.auto.commit": False,
            })
        if self._reader_next != offset:
            # position via (re-)assign with an explicit offset: seek()
            # right after assign() raises "Erroneous state" in real
            # confluent-kafka (the fetcher hasn't started); assign-with-
            # offset is always legal
            self._reader.assign([self._tp(self.topic, 0, offset)])
            self._reader_next = offset
        out: List[Tuple[int, Dict[str, Any]]] = []
        for msg in self._reader.consume(max_records, self.poll_timeout_s):
            if msg is None or msg.error():
                continue
            self._reader_next = msg.offset() + 1
            try:
                out.append(
                    (msg.offset(), json.loads(msg.value().decode("utf-8")))
                )
            except (ValueError, UnicodeDecodeError) as e:
                # surface the record instead of skipping it: a silent skip
                # leaves an offset HOLE the consumer's contiguous commit
                # can never cross (it would wedge at this offset forever).
                # Returned as a marker record, it fails scoring, exhausts
                # retries, dead-letters, and the commit advances past it.
                out.append((msg.offset(), {
                    "id": f"__undecodable-{msg.offset()}",
                    "__undecodable__": str(e),
                }))
        return out

    def _group_consumer(self, group: str):
        if group not in self._group_consumers:
            self._group_consumers[group] = self._consumer_cls({
                "bootstrap.servers": self.bootstrap,
                "group.id": group,
                "enable.auto.commit": False,
            })
        return self._group_consumers[group]

    def committed(self, group: str) -> int:
        c = self._group_consumer(group)
        tps = c.committed([self._tp(self.topic, 0)])
        off = tps[0].offset if tps else None
        # confluent uses OFFSET_INVALID (-1001) / -1 for "never committed"
        return off if off is not None and off >= 0 else 0

    def commit(self, group: str, offset: int) -> None:
        c = self._group_consumer(group)
        c.commit(offsets=[self._tp(self.topic, 0, offset)], asynchronous=False)


class KafkaIngestError(RuntimeError):
    """Producer-side delivery failure surfaced synchronously."""


class IngestConsumer:
    """Drain a broker through the engine with bounded concurrency.

    ``run()`` processes until ``stop()`` (or ``drain=True``: until the
    queue is exhausted). Results are appended to ``out_path`` as
    ``{"id", "offset", "response"}`` rows; failures exhaust ``retries``
    then dead-letter. Commit advances only past the contiguous prefix of
    handled offsets."""

    def __init__(
        self,
        broker: Broker,
        engine_host: str,
        engine_port: int,
        group: str = "default",
        out_path: str = "results.jsonl",
        dead_letter_path: Optional[str] = None,
        concurrency: int = 8,
        retries: int = 3,
        poll_batch: int = 64,
        idle_sleep_s: float = 0.05,
        retry_backoff_s: float = 0.05,
        engine_timeout_s: float = 30.0,
    ):
        self.broker = broker
        self.engine_host = engine_host
        self.engine_port = engine_port
        self.group = group
        self.out_path = out_path
        self.dead_letter_path = dead_letter_path or (
            os.path.join(os.path.dirname(out_path) or ".", "dead_letter.jsonl")
        )
        self.concurrency = int(concurrency)
        self.retries = int(retries)
        self.poll_batch = int(poll_batch)
        self.idle_sleep_s = idle_sleep_s
        self.retry_backoff_s = retry_backoff_s
        self.engine_timeout_s = engine_timeout_s
        self._stop = asyncio.Event()
        self.stats = {"scored": 0, "dead_lettered": 0, "replayed": 0}
        self._client = None
        self._prior_ids: set = set()
        self._out_f = None

    def stop(self) -> None:
        self._stop.set()

    # -- engine call --------------------------------------------------------

    async def _score(self, record: Dict[str, Any]) -> Dict[str, Any]:
        from .graph.client import RestClient

        if "__undecodable__" in record:
            # broker surfaced a payload it could not decode (see
            # KafkaBroker.poll): not retryable — straight to dead-letter
            raise ValueError(
                f"undecodable broker payload: {record['__undecodable__']}"
            )
        if self._client is None:
            self._client = RestClient(
                self.engine_host, self.engine_port,
                timeout=self.engine_timeout_s,
            )
        body = record.get("request") or {"data": {"ndarray": record.get("data")}}
        last: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                return await self._client.engine_predict(body)
            except Exception as e:  # noqa: BLE001 - every failure retries
                last = e
                await asyncio.sleep(self.retry_backoff_s * (attempt + 1))
        raise RuntimeError(f"engine call failed after {self.retries} tries: {last}")

    # -- sink ---------------------------------------------------------------

    def _write_result(self, offset: int, record: Dict[str, Any],
                      response: Dict[str, Any]) -> None:
        rid = record.get("id", f"offset-{offset}")
        if rid in self._prior_ids:
            # a restart re-scored an offset a previous life already sank:
            # at-least-once working as designed, surfaced for operators
            self.stats["replayed"] += 1
        row = {"id": rid, "offset": offset, "response": response}
        if self._out_f is None:
            self._out_f = open(self.out_path, "a", encoding="utf-8")
        self._out_f.write(json.dumps(row, separators=(",", ":")) + "\n")

    def _sync_results(self) -> None:
        # results must be durable BEFORE the commit offset advances past
        # them, or a crash loses sunk rows the replay will never re-score
        if self._out_f is not None:
            self._out_f.flush()
            os.fsync(self._out_f.fileno())

    def _dead_letter(self, offset: int, record: Dict[str, Any], error: str) -> None:
        self.stats["dead_lettered"] += 1
        # seldon-lint: disable=wall-clock (persisted dead-letter stamp, no interval math)
        row = {"offset": offset, "record": record, "error": error, "ts": time.time()}
        with open(self.dead_letter_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(row, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())

    # -- drain loop ---------------------------------------------------------

    async def run(self, drain: bool = False) -> Dict[str, int]:
        # ids a previous life already sank (fuels the replayed stat)
        self._prior_ids = set(read_results(self.out_path))
        sem = asyncio.Semaphore(self.concurrency)
        handled: Dict[int, bool] = {}
        commit = self.broker.committed(self.group)
        next_poll = commit
        inflight: set = set()

        async def handle(offset: int, record: Dict[str, Any]) -> None:
            async with sem:
                try:
                    resp = await self._score(record)
                    self._write_result(offset, record, resp)
                    self.stats["scored"] += 1
                except Exception as e:  # noqa: BLE001 -> dead letter
                    self._dead_letter(offset, record, str(e))
            handled[offset] = True

        def advance_commit() -> None:
            nonlocal commit
            new = commit
            while handled.get(new):
                del handled[new]
                new += 1
            if new != commit:
                commit = new
                self._sync_results()
                self.broker.commit(self.group, commit)

        empty_polls = 0
        try:
            while not self._stop.is_set():
                # poll only while in-flight slots are free (backpressure)
                free = self.concurrency - (len(inflight))
                batch = (
                    self.broker.poll(next_poll, min(self.poll_batch, max(free, 0)))
                    if free > 0 else []
                )
                if free > 0:
                    # only a poll that actually RAN counts toward the
                    # drain guard — a skipped poll (no free slots) is not
                    # evidence the queue is empty
                    empty_polls = 0 if batch else empty_polls + 1
                for off, rec in batch:
                    t = asyncio.ensure_future(handle(off, rec))
                    inflight.add(t)
                    t.add_done_callback(inflight.discard)
                    next_poll = off + 1
                if not batch:
                    if inflight:
                        await asyncio.wait(
                            list(inflight), return_when=asyncio.FIRST_COMPLETED
                        )
                    elif drain and empty_polls >= 2:
                        # TWO consecutive empty polls: against a real
                        # broker one empty consume() does not mean
                        # exhausted (fetcher warm-up, transient latency) —
                        # a single-poll break would drain 0 records and
                        # report success. FileQueue just pays one extra
                        # (cheap, synchronous) poll.
                        break
                    else:
                        try:
                            await asyncio.wait_for(
                                self._stop.wait(), self.idle_sleep_s
                            )
                        except asyncio.TimeoutError:
                            pass
                advance_commit()
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)
            advance_commit()
        finally:
            if self._out_f is not None:
                self._sync_results()
                self._out_f.close()
                self._out_f = None
            if self._client is not None:
                await self._client.close()
                self._client = None
        return dict(self.stats)


def read_results(path: str) -> Dict[str, Dict[str, Any]]:
    """Results keyed by record id — last write wins, which is exactly the
    idempotent-sink property that upgrades at-least-once to
    exactly-once-observable."""
    out: Dict[str, Dict[str, Any]] = {}
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail from a crash mid-write
                out[row["id"]] = row
    except FileNotFoundError:
        pass
    return out


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser("seldon-tpu-ingest")
    sub = p.add_subparsers(dest="cmd", required=True)

    pe = sub.add_parser("enqueue", help="append records to the queue")
    pe.add_argument("--queue-dir", default=None,
                    help="file-queue directory (required unless --kafka)")
    pe.add_argument("--file", required=True,
                    help="JSONL of records ({'id', 'request'|'data'})")
    pe.add_argument("--kafka", default=None,
                    help="bootstrap servers — use a Kafka topic instead of "
                    "the file queue (needs confluent_kafka)")
    pe.add_argument("--topic", default="seldon-requests")

    pc = sub.add_parser("consume", help="drain the queue through an engine")
    pc.add_argument("--queue-dir", default=None,
                    help="file-queue directory (required unless --kafka)")
    pc.add_argument("--engine", required=True, help="host:port of the engine")
    pc.add_argument("--group", default="default")
    pc.add_argument("--out", default="results.jsonl")
    pc.add_argument("--dead-letter", default=None)
    pc.add_argument("--concurrency", type=int, default=8)
    pc.add_argument("--drain", action="store_true",
                    help="exit when the queue is exhausted")
    pc.add_argument("--kafka", default=None,
                    help="bootstrap servers — consume a Kafka topic instead "
                    "of the file queue (needs confluent_kafka)")
    pc.add_argument("--topic", default="seldon-requests")

    args = p.parse_args(argv)
    logging.basicConfig(level="INFO")
    if not args.kafka and not args.queue_dir:
        p.error("--queue-dir is required unless --kafka is given")
    q: Broker = (
        KafkaBroker(args.topic, bootstrap=args.kafka)
        if args.kafka else FileQueue(args.queue_dir)
    )
    if args.cmd == "enqueue":
        records = []
        with open(args.file, encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    records.append(json.loads(line))
        first = q.append_many(records)
        print(f"enqueued {len(records)} records from offset {first}")
        return
    host, _, port = args.engine.partition(":")
    consumer = IngestConsumer(
        q, host, int(port or 8000), group=args.group, out_path=args.out,
        dead_letter_path=args.dead_letter, concurrency=args.concurrency,
    )
    stats = asyncio.run(consumer.run(drain=args.drain))
    print(json.dumps(stats))


if __name__ == "__main__":
    main()
