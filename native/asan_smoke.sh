#!/usr/bin/env bash
# ASAN/UBSAN smoke for the native engine: boot the instrumented binary,
# push a request mix through both fronts (valid + malformed), and fail on
# any sanitizer report (halt_on_error aborts the process, which the
# health checks below then catch).
set -euo pipefail
BIN=${1:?usage: asan_smoke.sh <engine-asan-binary>}
PORT=${ASAN_SMOKE_PORT:-9963}
GPORT=$((PORT + 1))

export ASAN_OPTIONS="halt_on_error=1:abort_on_error=1:detect_leaks=0"
export UBSAN_OPTIONS="halt_on_error=1:abort_on_error=1"

"$BIN" --port "$PORT" --grpc-port "$GPORT" \
  --spec '{"name":"asan","graph":{"name":"c","implementation":"AVERAGE_COMBINER","children":[{"name":"a","implementation":"SIMPLE_MODEL"},{"name":"b","implementation":"SIMPLE_MODEL"}]}}' &
PID=$!
trap 'kill $PID 2>/dev/null || true' EXIT

for i in $(seq 1 50); do
  curl -fsS "http://127.0.0.1:$PORT/ping" >/dev/null 2>&1 && break
  kill -0 $PID 2>/dev/null || { echo "engine died during boot"; exit 1; }
  sleep 0.1
done

# valid JSON predictions
for i in $(seq 1 50); do
  curl -fsS -X POST "http://127.0.0.1:$PORT/api/v0.1/predictions" \
    -H 'Content-Type: application/json' \
    -d '{"data":{"ndarray":[[1.0,2.0],[3.0,4.0]]}}' >/dev/null
done
# feedback + probes + metrics
curl -fsS -X POST "http://127.0.0.1:$PORT/api/v0.1/feedback" \
  -H 'Content-Type: application/json' -d '{"reward": 0.5}' >/dev/null
curl -fsS "http://127.0.0.1:$PORT/metrics" >/dev/null
curl -fsS "http://127.0.0.1:$PORT/inflight" >/dev/null
# multipart predictions (the C++ multipart parser under the sanitizer)
curl -fsS -X POST "http://127.0.0.1:$PORT/api/v0.1/predictions" \
  -F 'data={"ndarray": [[1.0, 2.0]]};type=application/json' >/dev/null
curl -s -X POST "http://127.0.0.1:$PORT/api/v0.1/predictions" \
  -H 'Content-Type: multipart/form-data; boundary=zz' \
  --data-binary $'--zz\r\nbroken' >/dev/null || true
# malformed inputs (each answered, none may trip the sanitizer)
curl -s -X POST "http://127.0.0.1:$PORT/api/v0.1/predictions" \
  -H 'Content-Type: application/json' -d '{broken' >/dev/null || true
curl -s -X POST "http://127.0.0.1:$PORT/api/v0.1/predictions" \
  -H 'Content-Type: application/x-protobuf' --data-binary $'\xff\xfe\x01' >/dev/null || true
head -c 2048 /dev/urandom | curl -s -X POST --data-binary @- \
  "http://127.0.0.1:$PORT/api/v0.1/predictions" >/dev/null || true
# raw garbage at the h2 port
head -c 512 /dev/urandom | timeout 2 bash -c "cat > /dev/tcp/127.0.0.1/$GPORT" || true
printf 'PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n\x00\x00\x04\x08\x00\x00\x00\x00\x00AB' \
  | timeout 2 bash -c "cat > /dev/tcp/127.0.0.1/$GPORT" || true

sleep 0.3
kill -0 $PID 2>/dev/null || { echo "engine crashed under smoke (sanitizer?)"; exit 1; }
# still healthy after the mix
curl -fsS "http://127.0.0.1:$PORT/ping" >/dev/null
kill $PID
wait $PID 2>/dev/null || true
echo "ASAN smoke passed"
