// seldon_core_tpu native engine: the data-plane request orchestrator.
//
// TPU-native counterpart of the reference's Java engine (reference:
// engine/src/main/java/io/seldon/engine/ — Spring Boot + Tomcat + Netty,
// recursive @Async graph walk in predictors/PredictiveUnitBean.java:81-197,
// external REST in api/rest/RestClientController.java:136-291). Rebuilt as
// a single-binary epoll HTTP/1.1 service: on the single-core hosts that
// front TPU VMs, a non-blocking C++ loop beats a JVM thread farm by an
// order of magnitude on the same headline benchmark (stub-model
// predictions, doc/source/reference/benchmarking.md).
//
//   * N event-loop threads (SO_REUSEPORT), keep-alive, pipelining-safe
//   * in-process builtin units (SIMPLE_MODEL / AVERAGE_COMBINER /
//     SIMPLE_ROUTER / RANDOM_ABTEST, parity with reference
//     predictors/SimpleModelUnit.java:33-57 etc.)
//   * REMOTE units forwarded over keep-alive HTTP, or h2c gRPC when the
//     endpoint declares transport GRPC (grpc_remote_call) (one upstream
//     connection per loop thread) — e.g. Python/TPU microservices
//   * meta merge: puid, requestPath, routing, tags
//     (reference: PredictiveUnitBean.java:354-372)
//   * /api/v0.1|v1.0/predictions, /ping /live /ready /pause /unpause,
//     /inflight (drain probe), /metrics (Prometheus text)
//   * binary protobuf front: Content-Type application/x-protobuf bodies
//     carry SeldonMessage bytes — raw tensors cross the native hop as
//     bytes, not base64-inside-JSON (the zero-copy encoding's native
//     transport; the reference's binary path was gRPC,
//     grpc/SeldonGrpcServer.java:40-143)
//
// Division of labor (deliberate, not a gap): TPU co-location — in-process
// JAX units, device-prefetch micro-batching, continuous generate lanes —
// lives in the PYTHON engine, where the model runtime is. This binary is
// the front/orchestration tier: stub + remote graphs, both wire fronts,
// and the h2c upstream/streaming paths above. A deployment pairs them
// (native front -> Python engine upstream) when it wants both; fusing
// remote-unit calls in C++ would re-batch what the Python engine's
// micro-batcher already fuses one hop later.
//
//   * --bench mode: in-binary loopback load generator (clients and server
//     share the process, mirroring the locust setup of
//     notebooks/benchmark_simple_model.ipynb without a cluster);
//     --bench-binary drives the protobuf front
//
// C ABI for ctypes at the bottom: sce_start / sce_stop / sce_version.

#include <arpa/inet.h>
#include <cerrno>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <cassert>
#include <cctype>
#include <chrono>
#include <cmath>
#include <cstdarg>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <google/protobuf/struct.pb.h>

#include "prediction.pb.h"

// ---------------------------------------------------------------------------
// Minimal JSON (subset: obj/arr/str/num/bool/null) — parse in place, fast
// serialize. The wire schema is small and known; no external deps.
// ---------------------------------------------------------------------------
namespace json {

struct Value;
using Object = std::vector<std::pair<std::string, Value>>;
using Array = std::vector<Value>;

struct Value {
  enum Type { Null, Bool, Num, Str, Arr, Obj } type = Null;
  bool b = false;
  double num = 0;
  std::string str;
  std::shared_ptr<Array> arr;
  std::shared_ptr<Object> obj;

  static Value object() { Value v; v.type = Obj; v.obj = std::make_shared<Object>(); return v; }
  static Value array() { Value v; v.type = Arr; v.arr = std::make_shared<Array>(); return v; }
  static Value number(double d) { Value v; v.type = Num; v.num = d; return v; }
  static Value string(std::string s) { Value v; v.type = Str; v.str = std::move(s); return v; }

  const Value* find(const std::string& key) const {
    if (type != Obj) return nullptr;
    for (auto& kv : *obj) if (kv.first == key) return &kv.second;
    return nullptr;
  }
  Value& set(const std::string& key, Value v) {
    if (type != Obj) { type = Obj; obj = std::make_shared<Object>(); }
    for (auto& kv : *obj) if (kv.first == key) { kv.second = std::move(v); return kv.second; }
    obj->emplace_back(key, std::move(v));
    return obj->back().second;
  }
};

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}
  void skip() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p; }

  Value parse() { skip(); Value v = value(); skip(); if (p != end) ok = false; return v; }

  Value value() {
    skip();
    if (p >= end) { ok = false; return {}; }
    switch (*p) {
      case '{': return object();
      case '[': return array();
      case '"': return strval();
      case 't': return lit("true", [] { Value v; v.type = Value::Bool; v.b = true; return v; }());
      case 'f': return lit("false", [] { Value v; v.type = Value::Bool; v.b = false; return v; }());
      case 'n': return lit("null", Value{});
      default: return numval();
    }
  }

  Value lit(const char* s, Value v) {
    size_t n = strlen(s);
    if (size_t(end - p) < n || strncmp(p, s, n) != 0) { ok = false; return {}; }
    p += n;
    return v;
  }

  Value numval() {
    char* out = nullptr;
    double d = strtod(p, &out);
    if (out == p) { ok = false; return {}; }
    p = out;
    return Value::number(d);
  }

  Value strval() {
    ++p;  // opening quote
    std::string s;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': s += '\n'; break;
          case 't': s += '\t'; break;
          case 'r': s += '\r'; break;
          case 'b': s += '\b'; break;
          case 'f': s += '\f'; break;
          case '/': s += '/'; break;
          case '\\': s += '\\'; break;
          case '"': s += '"'; break;
          case 'u': {
            if (end - p < 5) { ok = false; return {}; }
            unsigned code = 0;
            for (int i = 1; i <= 4; i++) {
              char c = p[i]; code <<= 4;
              if (c >= '0' && c <= '9') code |= c - '0';
              else if (c >= 'a' && c <= 'f') code |= c - 'a' + 10;
              else if (c >= 'A' && c <= 'F') code |= c - 'A' + 10;
              else { ok = false; return {}; }
            }
            p += 4;
            // UTF-8 encode (BMP only; surrogate pairs passed through raw)
            if (code < 0x80) s += char(code);
            else if (code < 0x800) { s += char(0xC0 | (code >> 6)); s += char(0x80 | (code & 0x3F)); }
            else { s += char(0xE0 | (code >> 12)); s += char(0x80 | ((code >> 6) & 0x3F)); s += char(0x80 | (code & 0x3F)); }
            break;
          }
          default: ok = false; return {};
        }
        ++p;
      } else {
        s += *p++;
      }
    }
    if (p >= end) { ok = false; return {}; }
    ++p;  // closing quote
    return Value::string(std::move(s));
  }

  Value array() {
    Value v = Value::array();
    ++p; skip();
    if (p < end && *p == ']') { ++p; return v; }
    while (ok) {
      v.arr->push_back(value());
      skip();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; break; }
      ok = false;
    }
    return v;
  }

  Value object() {
    Value v = Value::object();
    ++p; skip();
    if (p < end && *p == '}') { ++p; return v; }
    while (ok) {
      skip();
      if (p >= end || *p != '"') { ok = false; break; }
      Value key = strval();
      skip();
      if (p >= end || *p != ':') { ok = false; break; }
      ++p;
      v.obj->emplace_back(std::move(key.str), value());
      skip();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      ok = false;
    }
    return v;
  }
};

inline void escape_to(const std::string& s, std::string& out) {
  out += '"';
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if ((unsigned char)c < 0x20) { char buf[8]; snprintf(buf, sizeof buf, "\\u%04x", c); out += buf; }
        else out += c;
    }
  }
  out += '"';
}

inline void number_to(double d, std::string& out) {
  if (std::isfinite(d)) {
    // range guard BEFORE the cast: double->long long outside range is UB
    if (std::fabs(d) < 1e15 && d == (long long)d) {
      char buf[32]; snprintf(buf, sizeof buf, "%lld", (long long)d); out += buf;
      return;
    }
    char buf[32];
    snprintf(buf, sizeof buf, "%.15g", d);
    if (strtod(buf, nullptr) != d) snprintf(buf, sizeof buf, "%.17g", d);
    out += buf;
  } else {
    out += "null";
  }
}

inline void serialize_to(const Value& v, std::string& out) {
  switch (v.type) {
    case Value::Null: out += "null"; break;
    case Value::Bool: out += v.b ? "true" : "false"; break;
    case Value::Num: number_to(v.num, out); break;
    case Value::Str: escape_to(v.str, out); break;
    case Value::Arr: {
      out += '[';
      bool first = true;
      for (auto& e : *v.arr) { if (!first) out += ','; first = false; serialize_to(e, out); }
      out += ']';
      break;
    }
    case Value::Obj: {
      out += '{';
      bool first = true;
      for (auto& kv : *v.obj) {
        if (!first) out += ',';
        first = false;
        escape_to(kv.first, out);
        out += ':';
        serialize_to(kv.second, out);
      }
      out += '}';
      break;
    }
  }
}

inline std::string serialize(const Value& v) { std::string out; out.reserve(256); serialize_to(v, out); return out; }

}  // namespace json

// ---------------------------------------------------------------------------
// Graph model
// ---------------------------------------------------------------------------

struct Unit {
  std::string name;
  std::string type;  // MODEL / ROUTER / COMBINER / TRANSFORMER / OUTPUT_TRANSFORMER
  std::string impl;  // SIMPLE_MODEL / ... / empty
  std::string host;  // remote host
  int port = 0;
  bool remote = false;
  bool grpc_transport = false;  // endpoint.transport == GRPC: h2c upstream
  double ratio_a = 0.5;  // RANDOM_ABTEST
  std::vector<Unit> children;
};

static Unit parse_unit(const json::Value& v) {
  Unit u;
  if (auto* n = v.find("name")) u.name = n->str;
  if (auto* t = v.find("type")) u.type = t->str;
  if (auto* i = v.find("implementation")) u.impl = i->str;
  if (auto* params = v.find("parameters")) {
    if (params->type == json::Value::Arr)
      for (auto& p : *params->arr) {
        auto* pn = p.find("name");
        auto* pv = p.find("value");
        if (pn && pv && pn->str == "ratio_a")
          u.ratio_a = pv->type == json::Value::Num ? pv->num : strtod(pv->str.c_str(), nullptr);
      }
  }
  if (auto* ep = v.find("endpoint")) {
    const json::Value* tr = ep->find("transport");
    const json::Value* host = ep->find("service_host");
    const json::Value* port = ep->find("service_port");
    if (tr && (tr->str == "REST" || tr->str == "HTTP" || tr->str == "GRPC")) {
      u.remote = true;
      u.grpc_transport = tr->str == "GRPC";
      u.host = host ? host->str : "127.0.0.1";
      u.port = port ? int(port->num) : 9000;
    }
  }
  // infer type from implementation (webhook parity)
  if (u.type.empty()) {
    if (u.impl == "SIMPLE_ROUTER" || u.impl == "RANDOM_ABTEST") u.type = "ROUTER";
    else if (u.impl == "AVERAGE_COMBINER") u.type = "COMBINER";
    else u.type = "MODEL";
  }
  if (auto* ch = v.find("children"))
    if (ch->type == json::Value::Arr)
      for (auto& c : *ch->arr) u.children.push_back(parse_unit(c));
  return u;
}

// ---------------------------------------------------------------------------
// Engine
// ---------------------------------------------------------------------------

struct Metrics {
  std::atomic<uint64_t> requests{0};
  std::atomic<uint64_t> errors{0};
  std::atomic<uint64_t> feedback{0};
  // latency histogram, microsecond buckets (log2-spaced 1us..~8s)
  static constexpr int kBuckets = 24;
  std::atomic<uint64_t> lat[kBuckets]{};
  std::atomic<uint64_t> lat_sum_us{0};

  void observe_us(uint64_t us) {
    int b = us == 0 ? 0 : 63 - __builtin_clzll(us);
    if (b >= kBuckets) b = kBuckets - 1;
    lat[b].fetch_add(1, std::memory_order_relaxed);
    lat_sum_us.fetch_add(us, std::memory_order_relaxed);
  }
};

struct UpstreamConn {  // per-thread keep-alive connection to one remote unit
  int fd = -1;
  std::string host;
  int port = 0;
};

struct Engine;

struct RequestCtx {
  std::string puid;
  json::Value request_path = json::Value::object();
  json::Value routing = json::Value::object();
  json::Value tags = json::Value::object();
  json::Value metrics_arr = json::Value::array();
  Engine* engine = nullptr;
  std::mt19937* rng = nullptr;
  std::map<std::string, UpstreamConn>* upstreams = nullptr;
  std::string error;  // non-empty => fail request
  // inbound request arrived as binary protobuf: REMOTE unit hops forward
  // binary protobuf too (no JSON text/base64 on any hop; values re-encode
  // as float64 through the engine's numeric model — a dtype-preserving
  // bytes passthrough would need a raw node in the internal value type)
  bool binary = false;
};

struct Engine {
  Unit root;
  std::string deployment = "default";
  std::atomic<bool> paused{false};
  // live requests across all worker threads: orchestrators poll /inflight
  // after /pause for an exact rolling-update drain (matches the Python
  // engine's probe; reference preStop was a blind 10s sleep)
  std::atomic<int64_t> inflight{0};
  Metrics metrics;
  // request-body cap: env default, overridable per spec via the
  // seldon.io/rest-max-body annotation (parity with graph/service.py)
  size_t max_body_bytes = 0;  // set in engine_start
  int port = 8000;
  int threads = 1;
  std::atomic<bool> stopping{false};
  std::vector<std::thread> loops;
  std::vector<int> listen_fds;
  // graph readiness: a background checker probes every REMOTE unit (GET
  // /ready for REST units, TCP connect for gRPC units) on a 5s cadence and
  // gates this engine's /ready (parity with the Python engine's readiness
  // loop and the reference's SeldonGraphReadyChecker.java:24-115)
  std::atomic<bool> graph_ready{true};
  struct RemoteEndpoint { std::string host; int port; bool grpc; };
  std::vector<RemoteEndpoint> remote_endpoints;
  std::thread ready_thread;
};

// --- builtin units (parity: reference engine/.../predictors/*.java) --------

static json::Value simple_model_predict(const json::Value& msg, int batch) {
  // static 3-class output (reference: SimpleModelUnit.java:33-57)
  json::Value data = json::Value::object();
  json::Value names = json::Value::array();
  names.arr->push_back(json::Value::string("proba_0"));
  names.arr->push_back(json::Value::string("proba_1"));
  names.arr->push_back(json::Value::string("proba_2"));
  data.set("names", std::move(names));
  json::Value nd = json::Value::array();
  for (int i = 0; i < batch; i++) {
    json::Value row = json::Value::array();
    row.arr->push_back(json::Value::number(0.9));
    row.arr->push_back(json::Value::number(0.05));
    row.arr->push_back(json::Value::number(0.05));
    nd.arr->push_back(std::move(row));
  }
  data.set("ndarray", std::move(nd));
  json::Value out = json::Value::object();
  out.set("data", std::move(data));
  return out;
}

static int batch_of(const json::Value& msg) {
  if (auto* data = msg.find("data")) {
    if (auto* nd = data->find("ndarray"))
      if (nd->type == json::Value::Arr) return std::max<size_t>(1, nd->arr->size());
    if (auto* t = data->find("tensor"))
      if (auto* shape = t->find("shape"))
        if (shape->type == json::Value::Arr && !shape->arr->empty()) {
          // shape is client-supplied: clamp to what the values array can
          // actually back so a tiny request can't fabricate a huge batch
          double want = (*shape->arr)[0].num;
          size_t have = 1;
          if (auto* values = t->find("values"))
            if (values->type == json::Value::Arr) have = std::max<size_t>(1, values->arr->size());
          if (!(want >= 1)) return 1;
          return int(std::min(want, double(have)));
        }
  }
  return 1;
}

// numeric matrix view of a message's data (ndarray or tensor)
static bool msg_matrix(const json::Value& msg, std::vector<std::vector<double>>& out) {
  auto* data = msg.find("data");
  if (!data) return false;
  if (auto* nd = data->find("ndarray")) {
    if (nd->type != json::Value::Arr) return false;
    for (auto& row : *nd->arr) {
      std::vector<double> r;
      if (row.type == json::Value::Arr) {
        for (auto& x : *row.arr) r.push_back(x.num);
      } else {
        r.push_back(row.num);
      }
      out.push_back(std::move(r));
    }
    return true;
  }
  if (auto* t = data->find("tensor")) {
    auto* shape = t->find("shape");
    auto* values = t->find("values");
    if (!values || values->type != json::Value::Arr) return false;
    size_t rows = 1, cols = values->arr->size();
    if (shape && shape->type == json::Value::Arr && shape->arr->size() >= 2) {
      // matrix view of an N-d tensor: rows = dim0, cols = prod(trailing
      // dims), matching the Python payload layer's np.prod(shape) reshape
      double r = (*shape->arr)[0].num, c = 1.0;
      for (size_t d = 1; d < shape->arr->size(); d++) c *= (*shape->arr)[d].num;
      if (!(r >= 1) || !(c >= 1)) return false;  // rejects negatives and NaN
      // client-supplied shape must exactly match the values it claims to
      // describe — rejecting (-> 4xx/5xx upstream) both guards the
      // multi-GB-allocation DoS and avoids silently reshaping data
      if (r * c != double(values->arr->size())) return false;
      rows = size_t(r);
      cols = size_t(c);
    }
    size_t idx = 0;
    for (size_t i = 0; i < rows; i++) {
      std::vector<double> r;
      for (size_t j = 0; j < cols && idx < values->arr->size(); j++) r.push_back((*values->arr)[idx++].num);
      out.push_back(std::move(r));
    }
    return true;
  }
  return false;
}

static json::Value matrix_msg(const std::vector<std::vector<double>>& m, const json::Value* names) {
  json::Value nd = json::Value::array();
  for (auto& row : m) {
    json::Value r = json::Value::array();
    for (double x : row) r.arr->push_back(json::Value::number(x));
    nd.arr->push_back(std::move(r));
  }
  json::Value data = json::Value::object();
  if (names) data.set("names", *names);
  data.set("ndarray", std::move(nd));
  json::Value out = json::Value::object();
  out.set("data", std::move(data));
  return out;
}

// --- remote unit call (keep-alive, blocking on this loop thread) -----------

// Upstream I/O deadline. The reference gives every internal hop a
// configurable timeout (InternalPredictionService.java:87-91); without one a
// single hung microservice would stall this event-loop thread forever
// (including /live + /ready served from it) and make engine_stop unjoinable.
static int upstream_timeout_ms() {
  static int ms = [] {
    const char* e = getenv("SELDON_ENGINE_UPSTREAM_TIMEOUT_MS");
    int v = e ? atoi(e) : 0;
    return v > 0 ? v : 10000;
  }();
  return ms;
}

// Request-body cap (413 above it), python twin http_server.py
// DEFAULT_MAX_BODY_BYTES; same env knob as the wrapper's.
static size_t g_max_body_bytes = [] {
  const char* e = getenv("SELDON_REST_MAX_BODY");
  long v = e ? atol(e) : 0;
  return v > 0 ? (size_t)v : (size_t)(64u << 20);
}();

static void set_io_timeouts(int fd, int ms) {
  if (ms < 1) ms = 1;
  timeval tv{ms / 1000, (ms % 1000) * 1000};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
}

// IPv4 literal fast path, then getaddrinfo so in-cluster DNS service
// names (the reference's normal addressing mode) resolve too. Successful
// lookups are cached: cluster ClusterIPs are stable for a Service's
// lifetime, and the gRPC front calls this on its single-threaded event
// loop where a per-request synchronous DNS query would head-of-line
// block every in-flight stream (only the FIRST request per host pays).
static bool resolve_ipv4(const std::string& host, in_addr* out) {
  const char* h = host == "localhost" ? "127.0.0.1" : host.c_str();
  if (inet_pton(AF_INET, h, out) == 1) return true;
  // failures are cached too (5 s) or a misconfigured host would pay the
  // blocking resolver timeout on EVERY request instead of once per window
  static std::mutex mu;
  static std::map<std::string, in_addr> cache;
  static std::map<std::string, std::chrono::steady_clock::time_point> neg;
  {
    std::lock_guard<std::mutex> lk(mu);
    auto it = cache.find(host);
    if (it != cache.end()) { *out = it->second; return true; }
    auto nit = neg.find(host);
    if (nit != neg.end()) {
      if (std::chrono::steady_clock::now() < nit->second) return false;
      neg.erase(nit);
    }
  }
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(h, nullptr, &hints, &res) != 0 || !res) {
    std::lock_guard<std::mutex> lk(mu);
    neg[host] = std::chrono::steady_clock::now() + std::chrono::seconds(5);
    return false;
  }
  *out = ((sockaddr_in*)res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  std::lock_guard<std::mutex> lk(mu);
  cache[host] = *out;
  return true;
}

static int connect_to(const std::string& host, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  set_io_timeouts(fd, timeout_ms);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (!resolve_ipv4(host, &addr.sin_addr)) { close(fd); return -1; }
  // bounded connect: non-blocking + poll, then back to blocking-with-deadline
  fcntl(fd, F_SETFL, O_NONBLOCK);
  int rc = connect(fd, (sockaddr*)&addr, sizeof addr);
  if (rc != 0 && errno == EINPROGRESS) {
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, timeout_ms) != 1) { close(fd); return -1; }
    int err = 0; socklen_t len = sizeof err;
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) { close(fd); return -1; }
  } else if (rc != 0) { close(fd); return -1; }
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL) & ~O_NONBLOCK);
  return fd;
}

// decode a complete chunked-transfer payload accumulated in `raw`;
// returns true + decoded body once the terminating 0-chunk has arrived
static bool decode_chunked(const std::string& raw, std::string& body, bool& complete) {
  body.clear();
  size_t pos = 0;
  for (;;) {
    size_t line_end = raw.find("\r\n", pos);
    if (line_end == std::string::npos) { complete = false; return true; }
    size_t len = strtoul(raw.c_str() + pos, nullptr, 16);
    pos = line_end + 2;
    if (len == 0) {
      // consume trailers + the final CRLF — leaving them unread would
      // desync the next response on this keep-alive connection
      for (;;) {
        size_t te = raw.find("\r\n", pos);
        if (te == std::string::npos) { complete = false; return true; }
        if (te == pos) { complete = true; return true; }  // empty line
        pos = te + 2;  // skip a trailer header line
      }
    }
    if (raw.size() < pos + len + 2) { complete = false; return true; }
    body.append(raw, pos, len);
    pos += len + 2;  // chunk + CRLF
  }
}

using Deadline = std::chrono::steady_clock::time_point;

static bool past(const Deadline& d) { return std::chrono::steady_clock::now() >= d; }

static bool read_http_response(int fd, std::string& body, int& status, const Deadline& deadline) {
  std::string buf;
  char tmp[16384];
  size_t header_end = std::string::npos;
  while (header_end == std::string::npos) {
    ssize_t n = read(fd, tmp, sizeof tmp);
    if (n <= 0 || past(deadline)) return false;  // deadline bounds a trickling upstream
    buf.append(tmp, n);
    header_end = buf.find("\r\n\r\n");
    if (buf.size() > (1u << 26)) return false;
  }
  status = 0;
  if (buf.size() > 12) status = atoi(buf.c_str() + 9);
  const char* cl = strcasestr(buf.c_str(), "content-length:");
  const char* te = strcasestr(buf.c_str(), "transfer-encoding:");
  bool chunked = te && te < buf.c_str() + header_end && strcasestr(te, "chunked") == te + 18 + strspn(te + 18, " \t");
  if (cl && cl < buf.c_str() + header_end) {
    size_t content_length = strtoul(cl + 15, nullptr, 10);
    size_t have = buf.size() - (header_end + 4);
    body = buf.substr(header_end + 4);
    while (have < content_length) {
      ssize_t n = read(fd, tmp, sizeof tmp);
      if (n <= 0 || past(deadline)) return false;
      body.append(tmp, n);
      have += n;
    }
    return true;
  }
  if (chunked) {
    std::string raw = buf.substr(header_end + 4);
    for (;;) {
      bool complete = false;
      if (!decode_chunked(raw, body, complete)) return false;
      if (complete) return true;
      ssize_t n = read(fd, tmp, sizeof tmp);
      if (n <= 0 || past(deadline)) return false;
      raw.append(tmp, n);
      if (raw.size() > (1u << 26)) return false;
    }
  }
  // close-delimited (HTTP/1.0 style): read until EOF
  body = buf.substr(header_end + 4);
  for (;;) {
    ssize_t n = read(fd, tmp, sizeof tmp);
    if (n < 0 || past(deadline)) return false;
    if (n == 0) return true;
    body.append(tmp, n);
    if (body.size() > (1u << 26)) return false;
  }
}

// forward decls: binary-front conversions (defined with the proto front below)
static void result_to_proto(const json::Value& result, const std::string& reply_enc,
                            seldontpu::SeldonMessage& m);
static bool proto_to_value(const seldontpu::SeldonMessage& m, json::Value& out,
                           std::string& reply_enc, std::string& err);
// gRPC upstream client (defined in grpc_front.inc, same TU): h2c unary call
// to a REMOTE unit whose endpoint.transport is GRPC — the stub-per-type
// dispatch the reference engine does via Netty channels
// (InternalPredictionService.java:186-350)
static json::Value grpc_remote_call(RequestCtx& ctx, const Unit& u,
                                    const char* path, const json::Value& msg);

static json::Value remote_call(RequestCtx& ctx, const Unit& u, const char* path, const json::Value& msg) {
  if (u.grpc_transport) return grpc_remote_call(ctx, u, path, msg);
  std::string key = u.host + ":" + std::to_string(u.port);
  UpstreamConn& conn = (*ctx.upstreams)[key];
  // binary inbound -> binary upstream (except /aggregate: the list shape
  // keeps JSON); the wrapper mirrors the encoding on its response
  const bool bin_hop = ctx.binary && strcmp(path, "/aggregate") != 0;
  std::string body;
  const char* ctype = "application/json";
  if (bin_hop) {
    seldontpu::SeldonMessage pbmsg;
    result_to_proto(msg, "raw", pbmsg);
    pbmsg.SerializeToString(&body);
    ctype = "application/x-protobuf";
  } else {
    body = json::serialize(msg);
  }
  char head[256];
  // one deadline for the WHOLE hop (connect + 3 retries + reads) so a dead
  // or trickling upstream can't stack per-attempt timeouts into a 30s+
  // event-loop stall (reference applies its timeout per hop, not per try:
  // InternalPredictionService.java:87-91)
  const Deadline deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(upstream_timeout_ms());
  for (int attempt = 0; attempt < 3; attempt++) {
    // per-operation socket timeouts clamped to the REMAINING hop budget so
    // the hop can't exceed the deadline by stacking full-length waits
    int rem = int(std::chrono::duration_cast<std::chrono::milliseconds>(
                      deadline - std::chrono::steady_clock::now())
                      .count());
    if (rem <= 0) break;
    if (conn.fd < 0) conn.fd = connect_to(u.host, u.port, rem);
    else set_io_timeouts(conn.fd, rem);
    if (conn.fd < 0) continue;
    int n = snprintf(head, sizeof head,
                     "POST %s HTTP/1.1\r\nHost: %s\r\nContent-Type: %s\r\nContent-Length: %zu\r\n\r\n",
                     path, u.host.c_str(), ctype, body.size());
    std::string req(head, n);
    req += body;
    if (write(conn.fd, req.data(), req.size()) != (ssize_t)req.size()) { close(conn.fd); conn.fd = -1; continue; }
    std::string resp_body;
    int status = 0;
    if (!read_http_response(conn.fd, resp_body, status, deadline)) { close(conn.fd); conn.fd = -1; continue; }
    if (status >= 400) { ctx.error = "unit " + u.name + " returned " + std::to_string(status); return {}; }
    if (bin_hop) {
      seldontpu::SeldonMessage resp;
      std::string enc, err;
      json::Value out;
      if (!resp.ParseFromArray(resp_body.data(), int(resp_body.size())) ||
          !proto_to_value(resp, out, enc, err)) {
        ctx.error = "unit " + u.name + " returned invalid protobuf: " + err;
        return {};
      }
      return out;
    }
    json::Parser p(resp_body);
    json::Value out = p.parse();
    if (!p.ok) { ctx.error = "unit " + u.name + " returned invalid JSON"; return {}; }
    return out;
  }
  ctx.error = "unit " + u.name + " unreachable after 3 tries";
  return {};
}

// --- graph walk (parity: reference PredictiveUnitBean.getOutputAsync) ------

static void absorb_meta(RequestCtx& ctx, const json::Value& resp) {
  if (auto* meta = resp.find("meta")) {
    if (auto* tags = meta->find("tags"))
      if (tags->type == json::Value::Obj)
        for (auto& kv : *tags->obj) ctx.tags.set(kv.first, kv.second);
    if (auto* ms = meta->find("metrics"))
      if (ms->type == json::Value::Arr)
        for (auto& m : *ms->arr) ctx.metrics_arr.arr->push_back(m);
  }
}

static json::Value walk(RequestCtx& ctx, const Unit& u, json::Value msg);

static json::Value unit_predict(RequestCtx& ctx, const Unit& u, const json::Value& msg) {
  if (u.remote) {
    json::Value out = remote_call(ctx, u, "/predict", msg);
    if (ctx.error.empty()) absorb_meta(ctx, out);
    return out;
  }
  if (u.impl == "SIMPLE_MODEL") return simple_model_predict(msg, batch_of(msg));
  ctx.error = "unit " + u.name + " has no implementation and no endpoint";
  return {};
}

static int unit_route(RequestCtx& ctx, const Unit& u, const json::Value& msg) {
  if (u.remote) {
    json::Value out = remote_call(ctx, u, "/route", msg);
    if (!ctx.error.empty()) return 0;
    absorb_meta(ctx, out);
    std::vector<std::vector<double>> m;
    if (msg_matrix(out, m) && !m.empty() && !m[0].empty()) return int(m[0][0]);
    ctx.error = "router " + u.name + " returned no branch tensor";
    return 0;
  }
  if (u.impl == "RANDOM_ABTEST") {
    std::uniform_real_distribution<double> d(0.0, 1.0);
    return d(*ctx.rng) < u.ratio_a ? 0 : 1;
  }
  return 0;  // SIMPLE_ROUTER (reference: SimpleRouterUnit.java:25-30)
}

static json::Value unit_aggregate(RequestCtx& ctx, const Unit& u, std::vector<json::Value> outs) {
  if (u.remote) {
    json::Value list = json::Value::object();
    json::Value arr = json::Value::array();
    for (auto& o : outs) arr.arr->push_back(std::move(o));
    list.set("seldonMessages", std::move(arr));
    json::Value out = remote_call(ctx, u, "/aggregate", list);
    if (ctx.error.empty()) absorb_meta(ctx, out);
    return out;
  }
  // AVERAGE_COMBINER: element-wise mean (reference: AverageCombinerUnit.java:30)
  std::vector<std::vector<std::vector<double>>> mats(outs.size());
  for (size_t i = 0; i < outs.size(); i++) {
    if (!msg_matrix(outs[i], mats[i])) { ctx.error = "combiner input " + std::to_string(i) + " has no tensor data"; return {}; }
    if (mats[i].size() != mats[0].size()) { ctx.error = "combiner inputs disagree on shape"; return {}; }
    // every row, not just row 0 — ragged ndarrays must not reach the
    // accumulation loop's mats[m][i][j] indexing
    for (size_t r = 0; r < mats[i].size(); r++)
      if (mats[i][r].size() != mats[0][r].size()) { ctx.error = "combiner inputs disagree on shape"; return {}; }
  }
  auto avg = mats[0];
  for (size_t m = 1; m < mats.size(); m++)
    for (size_t i = 0; i < avg.size(); i++)
      for (size_t j = 0; j < avg[i].size(); j++) avg[i][j] += mats[m][i][j];
  for (auto& row : avg)
    for (auto& x : row) x /= double(mats.size());
  const json::Value* names = nullptr;
  if (auto* d0 = outs[0].find("data")) names = d0->find("names");
  return matrix_msg(avg, names);
}

static json::Value walk(RequestCtx& ctx, const Unit& u, json::Value msg) {
  ctx.request_path.set(u.name, json::Value::string(u.impl.empty() ? u.name : u.impl));

  // 1. input transform
  if (u.type == "MODEL") {
    msg = unit_predict(ctx, u, msg);
    if (!ctx.error.empty()) return {};
  } else if (u.type == "TRANSFORMER") {
    if (u.remote) {
      msg = remote_call(ctx, u, "/transform-input", msg);
      if (!ctx.error.empty()) return {};
      absorb_meta(ctx, msg);
    }
  }

  // 2/3. routing + children
  if (!u.children.empty()) {
    std::vector<const Unit*> selected;
    if (u.type == "ROUTER") {
      int branch = unit_route(ctx, u, msg);
      if (!ctx.error.empty()) return {};
      if (branch >= int(u.children.size()) || branch < -1) {
        ctx.error = "router " + u.name + " chose branch " + std::to_string(branch);
        return {};
      }
      ctx.routing.set(u.name, json::Value::number(branch));
      if (branch == -1)
        for (auto& c : u.children) selected.push_back(&c);
      else
        selected.push_back(&u.children[branch]);
    } else {
      for (auto& c : u.children) selected.push_back(&c);
    }
    std::vector<json::Value> outs;
    outs.reserve(selected.size());
    for (auto* c : selected) {
      outs.push_back(walk(ctx, *c, msg));
      if (!ctx.error.empty()) return {};
    }
    if (u.type == "COMBINER") {
      msg = unit_aggregate(ctx, u, std::move(outs));
      if (!ctx.error.empty()) return {};
    } else if (outs.size() == 1) {
      msg = std::move(outs[0]);
    } else {
      ctx.error = "unit " + u.name + " has multiple child outputs but is no combiner";
      return {};
    }
  }

  // 5. output transform
  if (u.type == "OUTPUT_TRANSFORMER" && u.remote) {
    msg = remote_call(ctx, u, "/transform-output", msg);
    if (!ctx.error.empty()) return {};
    absorb_meta(ctx, msg);
  }
  return msg;
}

// ---------------------------------------------------------------------------
// HTTP server (epoll, keep-alive)
// ---------------------------------------------------------------------------

struct Conn {
  int fd = -1;
  std::string in;
  std::string out;
  size_t out_off = 0;
  // incremental parse state: where the CRLFCRLF search left off and, once
  // headers are parsed, the total byte count of the pending request —
  // avoids O(n^2) rescans of large bodies arriving in many chunks
  size_t scan_off = 0;
  size_t need_total = 0;  // 0 = headers not yet parsed
  bool close_after_flush = false;
  bool want_epollout = false;
  // half-close drain: after a terminal error response (413 etc.) the
  // request body may still be inbound; close(fd) with unread data RSTs
  // the socket and can destroy the response before the client reads it.
  // Instead: shutdown(SHUT_WR), discard inbound until FIN/deadline.
  bool draining = false;
  size_t drained = 0;
  std::chrono::steady_clock::time_point drain_deadline{};
};

static std::atomic<uint64_t> g_puid_counter{1};
// process entropy for puids — separate from the seeded routing rng so A/B
// splits stay deterministic while puids stay unique across restarts
static const uint64_t g_puid_entropy = [] {
  std::random_device rd;
  return (uint64_t(rd()) << 32) ^ rd() ^ (uint64_t)getpid();
}();

static std::string gen_puid(std::mt19937&) {
  char buf[48];
  uint64_t c = g_puid_counter.fetch_add(1, std::memory_order_relaxed);
  snprintf(buf, sizeof buf, "%llx-%llx", (unsigned long long)g_puid_entropy,
           (unsigned long long)c);
  return buf;
}

static void http_response(std::string& out, int status, const std::string& body,
                          const char* ctype = "application/json") {
  const char* reason = status == 200 ? "OK" : status == 400 ? "Bad Request" : status == 404 ? "Not Found"
                       : status == 413 ? "Payload Too Large"
                       : status == 503 ? "Service Unavailable" : "Internal Server Error";
  char head[256];
  int n = snprintf(head, sizeof head,
                   "HTTP/1.1 %d %s\r\nContent-Type: %s\r\nContent-Length: %zu\r\nConnection: keep-alive\r\n\r\n",
                   status, reason, ctype, body.size());
  out.append(head, n);
  out += body;
}

static std::string error_json(int code, const std::string& info) {
  json::Value v = json::Value::object();
  json::Value status = json::Value::object();
  status.set("code", json::Value::number(code));
  status.set("info", json::Value::string(info));
  status.set("status", json::Value::string("FAILURE"));
  v.set("status", std::move(status));
  return json::serialize(v);
}

// ---------------------------------------------------------------------------
// Binary protobuf front: SeldonMessage <-> internal json::Value.
// Raw tensor bytes are decoded straight into the engine's numeric rows —
// no base64, no JSON text parse (the tax the VERDICT called out on the
// native hop).
// ---------------------------------------------------------------------------

static json::Value pbvalue_to_value(const google::protobuf::Value& v) {
  using PV = google::protobuf::Value;
  switch (v.kind_case()) {
    case PV::kNumberValue: return json::Value::number(v.number_value());
    case PV::kStringValue: return json::Value::string(v.string_value());
    case PV::kBoolValue: {
      json::Value b;
      b.type = json::Value::Bool;
      b.b = v.bool_value();
      return b;
    }
    case PV::kStructValue: {
      json::Value o = json::Value::object();
      for (auto& kv : v.struct_value().fields()) o.set(kv.first, pbvalue_to_value(kv.second));
      return o;
    }
    case PV::kListValue: {
      json::Value a = json::Value::array();
      for (auto& e : v.list_value().values()) a.arr->push_back(pbvalue_to_value(e));
      return a;
    }
    default: return json::Value();  // null
  }
}

static void value_to_pbvalue(const json::Value& v, google::protobuf::Value* out) {
  switch (v.type) {
    case json::Value::Num: out->set_number_value(v.num); break;
    case json::Value::Str: out->set_string_value(v.str); break;
    case json::Value::Bool: out->set_bool_value(v.b); break;
    case json::Value::Obj:
      for (auto& kv : *v.obj)
        value_to_pbvalue(kv.second, &(*out->mutable_struct_value()->mutable_fields())[kv.first]);
      break;
    case json::Value::Arr:
      for (auto& e : *v.arr) value_to_pbvalue(e, out->mutable_list_value()->add_values());
      break;
    default: out->set_null_value(google::protobuf::NULL_VALUE); break;
  }
}

// base64 (standard alphabet, padded) — the JSON edge's raw-bytes carrier
static const char kB64[] = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

static std::string b64_encode(const std::string& in) {
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  for (; i + 3 <= in.size(); i += 3) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8) | uint8_t(in[i + 2]);
    out += kB64[(v >> 18) & 63]; out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63]; out += kB64[v & 63];
  }
  if (i + 1 == in.size()) {
    uint32_t v = uint8_t(in[i]) << 16;
    out += kB64[(v >> 18) & 63]; out += kB64[(v >> 12) & 63]; out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8);
    out += kB64[(v >> 18) & 63]; out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63]; out += '=';
  }
  return out;
}

static bool b64_decode(const std::string& in, std::string& out) {
  // magic static: C++11 guarantees thread-safe initialization (the engine
  // runs one epoll loop per worker thread)
  static const std::array<int8_t, 256> lut = [] {
    std::array<int8_t, 256> t;
    t.fill(-1);
    for (int i = 0; i < 64; i++) t[uint8_t(kB64[i])] = int8_t(i);
    return t;
  }();
  out.clear();
  out.reserve(in.size() / 4 * 3);
  uint32_t acc = 0;
  int bits = 0;
  for (char c : in) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int8_t v = lut[uint8_t(c)];
    if (v < 0) return false;
    acc = (acc << 6) | uint32_t(v);
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += char((acc >> bits) & 0xff);
    }
  }
  return true;
}

// decode a RawTensor (rank 1 or 2) into internal numeric rows
static bool raw_to_rows(const seldontpu::RawTensor& r, json::Value& ndarray, std::string& err) {
  if (!r.encoding().empty()) {
    // compressed raw (zlib/jpeg-rows) is decoded host-side by the Python
    // model tier (payload.raw_to_array); builtin units on the native
    // front take plain LE bytes only — fail loudly, never misparse
    err = "raw encoding '" + r.encoding() + "' unsupported by native builtin units";
    return false;
  }
  int64_t rows = 1, cols = 1;
  if (r.shape_size() == 1) cols = r.shape(0);
  else if (r.shape_size() == 2) { rows = r.shape(0); cols = r.shape(1); }
  else { err = "raw tensor rank " + std::to_string(r.shape_size()) + " unsupported on native front"; return false; }
  const std::string& d = r.data();
  // validate the client-supplied shape BEFORE any allocation: negative or
  // oversized dims must not reach vector(count) (remote bad_alloc = DoS);
  // the body cap is 64 MiB so count can never legitimately exceed it
  if (rows < 0 || cols < 0 || (cols > 0 && rows > int64_t(1) << 26) ||
      (rows > 0 && cols > int64_t(1) << 26) ||
      uint64_t(rows) * uint64_t(cols) > d.size()) {
    err = "raw tensor shape [" + std::to_string(rows) + "," + std::to_string(cols) +
          "] inconsistent with " + std::to_string(d.size()) + " data bytes";
    return false;
  }
  size_t count = size_t(rows) * size_t(cols);
  auto need = [&](size_t itemsize) { return count * itemsize == d.size(); };
  std::vector<double> vals(count);
  const char* dt = r.dtype().c_str();
  if (!strcmp(dt, "float32") && need(4)) {
    const float* p = reinterpret_cast<const float*>(d.data());
    for (size_t i = 0; i < count; i++) vals[i] = p[i];
  } else if (!strcmp(dt, "float64") && need(8)) {
    memcpy(vals.data(), d.data(), d.size());
  } else if (!strcmp(dt, "int32") && need(4)) {
    const int32_t* p = reinterpret_cast<const int32_t*>(d.data());
    for (size_t i = 0; i < count; i++) vals[i] = p[i];
  } else if (!strcmp(dt, "int64") && need(8)) {
    const int64_t* p = reinterpret_cast<const int64_t*>(d.data());
    for (size_t i = 0; i < count; i++) vals[i] = double(p[i]);
  } else if (!strcmp(dt, "uint8") && need(1)) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(d.data());
    for (size_t i = 0; i < count; i++) vals[i] = p[i];
  } else if (!strcmp(dt, "bfloat16") && need(2)) {
    const uint16_t* p = reinterpret_cast<const uint16_t*>(d.data());
    for (size_t i = 0; i < count; i++) {
      uint32_t bits = uint32_t(p[i]) << 16;
      float f;
      memcpy(&f, &bits, 4);
      vals[i] = f;
    }
  } else {
    err = "raw dtype " + r.dtype() + " / " + std::to_string(d.size()) + " bytes mismatch";
    return false;
  }
  ndarray = json::Value::array();
  if (r.shape_size() == 1) {
    for (size_t j = 0; j < cols; j++) ndarray.arr->push_back(json::Value::number(vals[j]));
  } else {
    for (size_t i = 0; i < rows; i++) {
      json::Value row = json::Value::array();
      for (size_t j = 0; j < cols; j++) row.arr->push_back(json::Value::number(vals[i * cols + j]));
      ndarray.arr->push_back(std::move(row));
    }
  }
  return true;
}

// which encoding to mirror back: "raw" | "tensor" | "ndarray" | "" (non-data)
static bool proto_to_value(const seldontpu::SeldonMessage& m, json::Value& out,
                           std::string& reply_enc, std::string& err) {
  out = json::Value::object();
  if (m.has_meta()) {
    json::Value meta = json::Value::object();
    if (!m.meta().puid().empty()) meta.set("puid", json::Value::string(m.meta().puid()));
    if (!m.meta().tags().empty()) {
      json::Value tags = json::Value::object();
      for (auto& kv : m.meta().tags()) tags.set(kv.first, pbvalue_to_value(kv.second));
      meta.set("tags", std::move(tags));
    }
    if (!m.meta().request_path().empty()) {
      json::Value rp = json::Value::object();
      for (auto& kv : m.meta().request_path()) rp.set(kv.first, json::Value::string(kv.second));
      meta.set("requestPath", std::move(rp));
    }
    if (!m.meta().routing().empty()) {
      json::Value ro = json::Value::object();
      for (auto& kv : m.meta().routing()) ro.set(kv.first, json::Value::number(kv.second));
      meta.set("routing", std::move(ro));
    }
    if (m.meta().metrics_size() > 0) {
      // custom metrics from remote units must survive the binary hop
      // (absorb_meta forwards them into the response Meta)
      json::Value ms = json::Value::array();
      for (auto& metric : m.meta().metrics()) {
        json::Value one = json::Value::object();
        one.set("key", json::Value::string(metric.key()));
        one.set("type", json::Value::string(
            metric.type() == seldontpu::Metric::GAUGE ? "GAUGE"
            : metric.type() == seldontpu::Metric::TIMER ? "TIMER" : "COUNTER"));
        one.set("value", json::Value::number(metric.value()));
        ms.arr->push_back(std::move(one));
      }
      meta.set("metrics", std::move(ms));
    }
    out.set("meta", std::move(meta));
  }
  switch (m.data_oneof_case()) {
    case seldontpu::SeldonMessage::kData: {
      json::Value data = json::Value::object();
      json::Value names = json::Value::array();
      for (auto& n : m.data().names()) names.arr->push_back(json::Value::string(n));
      data.set("names", std::move(names));
      if (m.data().has_raw()) {
        json::Value nd;
        if (!raw_to_rows(m.data().raw(), nd, err)) return false;
        data.set("ndarray", std::move(nd));
        reply_enc = "raw";
      } else if (m.data().has_tensor()) {
        json::Value t = json::Value::object();
        json::Value shape = json::Value::array(), values = json::Value::array();
        for (auto s : m.data().tensor().shape()) shape.arr->push_back(json::Value::number(s));
        for (auto v : m.data().tensor().values()) values.arr->push_back(json::Value::number(v));
        t.set("shape", std::move(shape));
        t.set("values", std::move(values));
        data.set("tensor", std::move(t));
        reply_enc = "tensor";
      } else if (m.data().has_ndarray()) {
        google::protobuf::Value wrap;
        *wrap.mutable_list_value() = m.data().ndarray();
        data.set("ndarray", pbvalue_to_value(wrap));
        reply_enc = "ndarray";
      } else {
        err = "DefaultData carries no tensor/ndarray/raw";
        return false;
      }
      out.set("data", std::move(data));
      return true;
    }
    case seldontpu::SeldonMessage::kStrData:
      out.set("strData", json::Value::string(m.str_data()));
      return true;
    case seldontpu::SeldonMessage::kJsonData: {
      json::Parser p(m.json_data());
      json::Value v = p.parse();
      if (!p.ok) { err = "jsonData is not valid JSON"; return false; }
      out.set("jsonData", std::move(v));
      return true;
    }
    case seldontpu::SeldonMessage::kBinData:
      err = "binData unsupported on the native binary front";
      return false;
    default:
      return true;  // empty message (health-probe predict)
  }
}

// matrix rows out of an internal result (ndarray of rows, or flat row)
static bool result_rows(const json::Value& data, std::vector<std::vector<double>>& rows) {
  const json::Value* nd = data.find("ndarray");
  if (nd && nd->type == json::Value::Arr) {
    for (auto& r : *nd->arr) {
      if (r.type == json::Value::Arr) {
        std::vector<double> row;
        for (auto& x : *r.arr) {
          if (x.type != json::Value::Num) return false;
          row.push_back(x.num);
        }
        rows.push_back(std::move(row));
      } else if (r.type == json::Value::Num) {
        if (rows.empty()) rows.emplace_back();
        rows[0].push_back(r.num);
      } else return false;
    }
    return true;
  }
  const json::Value* t = data.find("tensor");
  if (t && t->type == json::Value::Obj) {
    const json::Value* shape = t->find("shape");
    const json::Value* values = t->find("values");
    if (!shape || shape->type != json::Value::Arr ||
        !values || values->type != json::Value::Arr) return false;
    for (auto& v : *values->arr)
      if (v.type != json::Value::Num) return false;
    size_t r = shape->arr->size() == 2 ? size_t((*shape->arr)[0].num) : 1;
    size_t c = shape->arr->size() == 2 ? size_t((*shape->arr)[1].num)
                                       : values->arr->size();
    if (r * c != values->arr->size()) return false;
    for (size_t i = 0; i < r; i++) {
      std::vector<double> row;
      for (size_t j = 0; j < c; j++) row.push_back((*values->arr)[i * c + j].num);
      rows.push_back(std::move(row));
    }
    return true;
  }
  return false;
}

static void result_to_proto(const json::Value& result, const std::string& reply_enc,
                            seldontpu::SeldonMessage& m) {
  if (const json::Value* meta = result.find("meta")) {
    auto* pm = m.mutable_meta();
    if (const json::Value* p = meta->find("puid"))
      if (p->type == json::Value::Str) pm->set_puid(p->str);
    if (const json::Value* tags = meta->find("tags"))
      if (tags->type == json::Value::Obj)
        for (auto& kv : *tags->obj) value_to_pbvalue(kv.second, &(*pm->mutable_tags())[kv.first]);
    if (const json::Value* rp = meta->find("requestPath"))
      if (rp->type == json::Value::Obj)
        for (auto& kv : *rp->obj)
          if (kv.second.type == json::Value::Str)
            (*pm->mutable_request_path())[kv.first] = kv.second.str;
    if (const json::Value* ro = meta->find("routing"))
      if (ro->type == json::Value::Obj)
        for (auto& kv : *ro->obj)
          if (kv.second.type == json::Value::Num)
            (*pm->mutable_routing())[kv.first] = int32_t(kv.second.num);
  }
  if (const json::Value* str = result.find("strData")) {
    if (str->type == json::Value::Str) m.set_str_data(str->str);
    return;
  }
  if (const json::Value* jd = result.find("jsonData")) {
    m.set_json_data(json::serialize(*jd));
    return;
  }
  const json::Value* data = result.find("data");
  if (!data) return;
  auto* pd = m.mutable_data();
  if (const json::Value* names = data->find("names"))
    if (names->type == json::Value::Arr)
      for (auto& n : *names->arr)
        if (n.type == json::Value::Str) pd->add_names(n.str);
  // flat (rank-1) ndarrays must stay rank-1 on the wire: a model behind a
  // binary client must see the same input shape a JSON client produces
  bool flat = false;
  if (const json::Value* nd = data->find("ndarray"))
    if (nd->type == json::Value::Arr && !nd->arr->empty())
      flat = (*nd->arr)[0].type == json::Value::Num;
  std::vector<std::vector<double>> rows;
  if (!result_rows(*data, rows)) {
    // non-numeric payload (e.g. string labels from a remote unit): carry
    // it generically as an ndarray ListValue instead of dropping the data
    if (const json::Value* nd = data->find("ndarray")) {
      google::protobuf::Value wrap;
      value_to_pbvalue(*nd, &wrap);
      if (wrap.has_list_value()) *pd->mutable_ndarray() = wrap.list_value();
    }
    return;
  }
  if (reply_enc == "raw") {
    auto* raw = pd->mutable_raw();
    raw->set_dtype("float64");
    if (!flat) raw->add_shape(int(rows.size()));
    raw->add_shape(rows.empty() ? 0 : int(rows[0].size()));
    std::string bytes;
    for (auto& row : rows)
      bytes.append(reinterpret_cast<const char*>(row.data()), row.size() * sizeof(double));
    raw->set_data(std::move(bytes));
  } else if (reply_enc == "ndarray") {
    auto* lv = pd->mutable_ndarray();
    for (auto& row : rows) {
      auto* lrow = lv->add_values()->mutable_list_value();
      for (double x : row) lrow->add_values()->set_number_value(x);
    }
  } else {  // tensor (default)
    auto* t = pd->mutable_tensor();
    if (!flat) t->add_shape(int(rows.size()));
    t->add_shape(rows.empty() ? 0 : int(rows[0].size()));
    for (auto& row : rows)
      for (double x : row) t->add_values(x);
  }
}

static std::string proto_error_bytes(int code, const std::string& info) {
  seldontpu::SeldonMessage m;
  auto* st = m.mutable_status();
  st->set_code(code);
  st->set_info(info);
  st->set_status(seldontpu::Status::FAILURE);
  std::string out;
  m.SerializeToString(&out);
  return out;
}

struct InflightGuard {
  std::atomic<int64_t>& n;
  explicit InflightGuard(std::atomic<int64_t>& n_) : n(n_) { n.fetch_add(1); }
  ~InflightGuard() { n.fetch_sub(1); }
};

// Binary-protobuf predict core: SeldonMessage bytes in -> SeldonMessage
// bytes out, with the same walk/meta/metrics semantics as the HTTP front.
// Used by the gRPC front (grpc_front.inc); handle_predictions keeps its
// own flow because its error paths speak HTTP.
static bool predict_proto(Engine& eng, RequestCtx& ctx, const std::string& in_pb,
                          std::string& out_pb, std::string& err) {
  auto t0 = std::chrono::steady_clock::now();
  seldontpu::SeldonMessage pbmsg;
  if (!pbmsg.ParseFromArray(in_pb.data(), int(in_pb.size()))) {
    eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
    err = "invalid SeldonMessage protobuf";
    return false;
  }
  json::Value msg;
  std::string reply_enc;
  if (!proto_to_value(pbmsg, msg, reply_enc, err)) {
    eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
    err = "invalid " + err;
    return false;
  }
  if (auto* meta = msg.find("meta"))
    if (auto* p = meta->find("puid")) ctx.puid = p->str;
  if (ctx.puid.empty()) ctx.puid = gen_puid(*ctx.rng);
  if (auto* meta = msg.find("meta"))
    if (auto* tags = meta->find("tags"))
      if (tags->type == json::Value::Obj)
        for (auto& kv : *tags->obj) ctx.tags.set(kv.first, kv.second);
  json::Value result = walk(ctx, eng.root, std::move(msg));
  if (!ctx.error.empty()) {
    eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
    err = ctx.error;
    return false;
  }
  json::Value meta = json::Value::object();
  meta.set("puid", json::Value::string(ctx.puid));
  if (!ctx.tags.obj->empty()) meta.set("tags", std::move(ctx.tags));
  if (!ctx.metrics_arr.arr->empty()) meta.set("metrics", std::move(ctx.metrics_arr));
  if (!ctx.routing.obj->empty()) meta.set("routing", std::move(ctx.routing));
  meta.set("requestPath", std::move(ctx.request_path));
  result.set("meta", std::move(meta));
  seldontpu::SeldonMessage resp;
  result_to_proto(result, reply_enc, resp);
  resp.SerializeToString(&out_pb);
  auto us = std::chrono::duration_cast<std::chrono::microseconds>(
      std::chrono::steady_clock::now() - t0).count();
  eng.metrics.observe_us(uint64_t(us));
  return true;
}

// multipart/form-data predictions: compose a SeldonMessage JSON body from
// parts named after its fields — json, jsonData, data, strData, binData,
// meta (parity with the Python fronts and the reference's multipart
// controller, RestClientController.java:136-206). Payloads are byte-exact:
// a part ends at the CRLF preceding the next boundary.
static bool multipart_to_json(const std::string& body, const std::string& boundary,
                              std::string& out_json, std::string& err) {
  const std::string delim = "\r\n--" + boundary;
  std::map<std::string, std::string> parts;
  // scan IN PLACE (no prepended copy of a possibly large upload): the
  // first boundary has no leading CRLF, later ones do
  size_t start;
  if (body.compare(0, boundary.size() + 2, "--" + boundary) == 0)
    start = boundary.size() + 2;
  else {
    size_t b0 = body.find(delim);
    if (b0 == std::string::npos) { err = "no multipart boundary found"; return false; }
    start = b0 + delim.size();
  }
  while (true) {
    if (body.compare(start, 2, "--") == 0) break;  // closing boundary
    if (body.compare(start, 2, "\r\n") == 0) start += 2;
    size_t hdr_end = body.find("\r\n\r\n", start);
    if (hdr_end == std::string::npos) break;
    size_t next = body.find(delim, hdr_end + 4);
    if (next == std::string::npos) break;
    std::string head = body.substr(start, hdr_end - start);
    std::string payload = body.substr(hdr_end + 4, next - hdr_end - 4);
    // the FIELD name parameter: require a separator before "name=" so
    // filename="..." (which may precede name=, RFC 7578 fixes no order)
    // never masquerades as the field name
    size_t np = 0;
    std::string fieldname;
    while ((np = head.find("name=\"", np)) != std::string::npos) {
      if (np == 0 || head[np - 1] == ' ' || head[np - 1] == ';') {
        size_t ne = head.find('"', np + 6);
        if (ne != std::string::npos) fieldname = head.substr(np + 6, ne - np - 6);
        break;
      }
      np += 6;
    }
    if (!fieldname.empty()) parts[fieldname] = std::move(payload);
    start = next + delim.size();
  }
  auto it = parts.find("json");
  if (it != parts.end()) {  // a whole SeldonMessage as one part
    out_json = it->second;
    return true;
  }
  json::Value msg = json::Value::object();
  bool have = false;
  for (const char* field : {"jsonData", "data", "meta"}) {
    auto p = parts.find(field);
    if (p == parts.end()) continue;
    json::Parser sub(p->second);
    json::Value v = sub.parse();
    if (!sub.ok) {
      err = std::string(field) + " part is not valid JSON";
      return false;
    }
    msg.set(field, std::move(v));
    if (strcmp(field, "meta") != 0) have = true;
  }
  if (!have) {
    auto ps = parts.find("strData");
    if (ps != parts.end()) {
      msg.set("strData", json::Value::string(ps->second));
      have = true;
    }
  }
  if (!have) {
    auto pb = parts.find("binData");
    if (pb != parts.end()) {
      msg.set("binData", json::Value::string(b64_encode(pb->second)));
      have = true;
    }
  }
  if (!have) {
    err = "multipart body has no json/jsonData/data/strData/binData part";
    return false;
  }
  out_json = json::serialize(msg);
  return true;
}

static void handle_predictions(Engine& eng, RequestCtx& ctx, const std::string& body,
                               std::string& out, bool binary = false) {
  InflightGuard guard(eng.inflight);
  auto t0 = std::chrono::steady_clock::now();
  json::Value msg;
  std::string reply_enc;
  if (binary) {
    seldontpu::SeldonMessage pbmsg;
    std::string err;
    if (!pbmsg.ParseFromArray(body.data(), int(body.size()))) {
      eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
      http_response(out, 400, proto_error_bytes(400, "invalid protobuf body"), "application/x-protobuf");
      return;
    }
    if (!proto_to_value(pbmsg, msg, reply_enc, err)) {
      eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
      http_response(out, 400, proto_error_bytes(400, err), "application/x-protobuf");
      return;
    }
  } else {
    json::Parser parser(body);
    msg = parser.parse();
    if (!parser.ok || msg.type != json::Value::Obj) {
      eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
      http_response(out, 400, error_json(400, "invalid JSON body"));
      return;
    }
    // JSON edge carries raw tensors base64-encoded: decode here so the
    // builtin units (and batch detection) see numeric rows exactly like
    // the binary front's raw_to_rows path; the reply mirrors raw back
    const json::Value* data_c = msg.find("data");
    const json::Value* raw = data_c ? data_c->find("raw") : nullptr;
    if (raw && raw->type == json::Value::Obj) {
      seldontpu::RawTensor rt;
      if (const json::Value* dt = raw->find("dtype"))
        if (dt->type == json::Value::Str) rt.set_dtype(dt->str);
      if (const json::Value* sh = raw->find("shape"))
        if (sh->type == json::Value::Arr)
          for (auto& s : *sh->arr) rt.add_shape(int64_t(s.num));
      std::string bytes;
      if (const json::Value* d = raw->find("data")) {
        if (d->type != json::Value::Str || !b64_decode(d->str, bytes)) {
          eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
          http_response(out, 400, error_json(400, "raw.data is not valid base64"));
          return;
        }
      }
      rt.set_data(std::move(bytes));
      std::string err;
      json::Value nd;
      if (!raw_to_rows(rt, nd, err)) {
        eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
        http_response(out, 400, error_json(400, err));
        return;
      }
      // rebuild data without the raw member (Object is a flat vector)
      json::Value new_data = json::Value::object();
      for (auto& kv : *data_c->obj)
        if (kv.first != "raw") new_data.set(kv.first, kv.second);
      new_data.set("ndarray", std::move(nd));
      msg.set("data", std::move(new_data));
      reply_enc = "raw_json";
    }
  }
  // puid (reference: PredictionService.PuidGenerator:77)
  if (auto* meta = msg.find("meta"))
    if (auto* p = meta->find("puid")) ctx.puid = p->str;
  if (ctx.puid.empty()) ctx.puid = gen_puid(*ctx.rng);
  if (auto* meta = msg.find("meta"))
    if (auto* tags = meta->find("tags"))
      if (tags->type == json::Value::Obj)
        for (auto& kv : *tags->obj) ctx.tags.set(kv.first, kv.second);

  json::Value result = walk(ctx, eng.root, std::move(msg));
  if (!ctx.error.empty()) {
    eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
    if (binary)
      http_response(out, 503, proto_error_bytes(503, ctx.error), "application/x-protobuf");
    else
      http_response(out, 503, error_json(503, ctx.error));
    return;
  }
  json::Value meta = json::Value::object();
  meta.set("puid", json::Value::string(ctx.puid));
  if (!ctx.tags.obj->empty()) meta.set("tags", std::move(ctx.tags));
  if (!ctx.metrics_arr.arr->empty()) meta.set("metrics", std::move(ctx.metrics_arr));
  if (!ctx.routing.obj->empty()) meta.set("routing", std::move(ctx.routing));
  meta.set("requestPath", std::move(ctx.request_path));
  result.set("meta", std::move(meta));

  if (binary) {
    seldontpu::SeldonMessage resp;
    result_to_proto(result, reply_enc, resp);
    std::string bytes;
    resp.SerializeToString(&bytes);
    http_response(out, 200, bytes, "application/x-protobuf");
  } else {
    if (reply_enc == "raw_json") {
      // mirror the request's raw encoding on the JSON edge: numeric rows
      // go back as base64 float64 bytes, like the Python engine does
      if (const json::Value* data = result.find("data")) {
        std::vector<std::vector<double>> rows;
        if (result_rows(*data, rows)) {
          std::string bytes;
          for (auto& row : rows)
            bytes.append(reinterpret_cast<const char*>(row.data()),
                         row.size() * sizeof(double));
          json::Value rawv = json::Value::object();
          rawv.set("dtype", json::Value::string("float64"));
          json::Value shape = json::Value::array();
          shape.arr->push_back(json::Value::number(double(rows.size())));
          shape.arr->push_back(json::Value::number(rows.empty() ? 0 : double(rows[0].size())));
          rawv.set("shape", std::move(shape));
          rawv.set("data", json::Value::string(b64_encode(bytes)));
          json::Value new_data = json::Value::object();
          for (auto& kv : *data->obj)
            if (kv.first != "ndarray" && kv.first != "tensor")
              new_data.set(kv.first, kv.second);
          new_data.set("raw", std::move(rawv));
          result.set("data", std::move(new_data));
        }
      }
    }
    http_response(out, 200, json::serialize(result));
  }
  eng.metrics.requests.fetch_add(1, std::memory_order_relaxed);
  auto us = std::chrono::duration_cast<std::chrono::microseconds>(std::chrono::steady_clock::now() - t0).count();
  eng.metrics.observe_us(uint64_t(us));
}

// Prometheus label values need \\, \" and newline escaped or one odd
// deployment name corrupts the whole exposition page
static std::string prom_label_escape(const std::string& v) {
  std::string out;
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

static std::string prometheus_text(Engine& eng) {
  std::string s;
  char buf[160];
  // deployment name is user-controlled; build labeled lines in std::string
  // so long names can't truncate the exposition format
  const std::string dep = prom_label_escape(eng.deployment);
  s += "# TYPE seldon_api_engine_server_requests counter\nseldon_api_engine_server_requests{deployment=\"";
  s += dep;
  s += "\"} " + std::to_string(eng.metrics.requests.load()) + "\n";
  s += "# TYPE seldon_api_engine_server_errors counter\nseldon_api_engine_server_errors{deployment=\"";
  s += dep;
  s += "\"} " + std::to_string(eng.metrics.errors.load()) + "\n";
  s += "# TYPE seldon_api_engine_server_feedback counter\nseldon_api_engine_server_feedback{deployment=\"";
  s += dep;
  s += "\"} " + std::to_string(eng.metrics.feedback.load()) + "\n";
  s += "# TYPE seldon_api_engine_server_requests_seconds histogram\n";
  uint64_t cum = 0;
  for (int b = 0; b < Metrics::kBuckets; b++) {
    cum += eng.metrics.lat[b].load();
    double le = std::pow(2.0, b + 1) / 1e6;
    snprintf(buf, sizeof buf, "seldon_api_engine_server_requests_seconds_bucket{le=\"%g\"} %llu\n", le, (unsigned long long)cum);
    s += buf;
  }
  snprintf(buf, sizeof buf, "seldon_api_engine_server_requests_seconds_bucket{le=\"+Inf\"} %llu\n", (unsigned long long)cum);
  s += buf;
  snprintf(buf, sizeof buf, "seldon_api_engine_server_requests_seconds_sum %g\n", eng.metrics.lat_sum_us.load() / 1e6);
  s += buf;
  snprintf(buf, sizeof buf, "seldon_api_engine_server_requests_seconds_count %llu\n", (unsigned long long)cum);
  s += buf;
  return s;
}

// returns false if the connection should close
static bool process_buffer(Engine& eng, Conn& c, std::mt19937& rng,
                           std::map<std::string, UpstreamConn>& upstreams) {
  for (;;) {
    size_t header_end;
    if (c.need_total == 0) {
      // resume the CRLFCRLF search where the previous chunk left off
      size_t start = c.scan_off > 3 ? c.scan_off - 3 : 0;
      header_end = c.in.find("\r\n\r\n", start);
      c.scan_off = c.in.size();
      if (header_end == std::string::npos) {
        if (c.in.size() > (1u << 20)) { http_response(c.out, 400, error_json(400, "headers too large")); return false; }
        return true;
      }
      size_t content_length = 0;
      {
        const char* cl = strcasestr(c.in.c_str(), "content-length:");
        if (cl && cl < c.in.c_str() + header_end) content_length = strtoul(cl + 15, nullptr, 10);
      }
      if (content_length > eng.max_body_bytes) {
        // 413 before buffering: one Content-Length must not OOM the engine
        // (python twin: http_server.py max_body_bytes)
        http_response(c.out, 413, error_json(413, "body too large"));
        return false;
      }
      c.need_total = header_end + 4 + content_length;
    }
    if (c.in.size() < c.need_total) return true;  // need more bytes
    header_end = c.in.find("\r\n\r\n");
    bool binary = false;
    std::string mp_boundary;
    {
      const char* ct = strcasestr(c.in.c_str(), "content-type:");
      if (ct && ct < c.in.c_str() + header_end) {
        ct += 13;
        while (*ct == ' ') ct++;
        binary = !strncasecmp(ct, "application/x-protobuf", 22) ||
                 !strncasecmp(ct, "application/octet-stream", 24);
        if (!strncasecmp(ct, "multipart/form-data", 19)) {
          const char* eol = strstr(ct, "\r\n");
          const char* bd = strcasestr(ct, "boundary=");
          if (bd && (!eol || bd < eol)) {
            bd += 9;
            if (*bd == '"') bd++;
            const char* end = bd;
            while (*end && *end != '"' && *end != ';' && *end != '\r') end++;
            mp_boundary.assign(bd, end - bd);
          }
        }
      }
    }

    // request line
    size_t sp1 = c.in.find(' ');
    size_t sp2 = c.in.find(' ', sp1 + 1);
    if (sp1 == std::string::npos || sp2 == std::string::npos || sp1 > header_end) {
      http_response(c.out, 400, error_json(400, "bad request line"));
      return false;
    }
    std::string method = c.in.substr(0, sp1);
    std::string path = c.in.substr(sp1 + 1, sp2 - sp1 - 1);
    size_t q = path.find('?');
    if (q != std::string::npos) path.resize(q);

    std::string body = c.in.substr(header_end + 4, c.need_total - header_end - 4);
    c.in.erase(0, c.need_total);
    c.need_total = 0;
    c.scan_off = 0;

    if (path == "/api/v0.1/predictions" || path == "/api/v1.0/predictions" || path == "/predict") {
      if (eng.paused.load(std::memory_order_relaxed)) {
        // binary clients parse SeldonMessage bodies, not JSON
        if (binary) http_response(c.out, 503, proto_error_bytes(503, "paused"), "application/x-protobuf");
        else http_response(c.out, 503, error_json(503, "paused"));
      } else {
        RequestCtx ctx;
        ctx.engine = &eng;
        ctx.rng = &rng;
        ctx.upstreams = &upstreams;
        ctx.binary = binary;
        if (!mp_boundary.empty()) {
          std::string json_body, mp_err;
          if (!multipart_to_json(body, mp_boundary, json_body, mp_err)) {
            eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
            http_response(c.out, 400, error_json(400, mp_err));
          } else {
            handle_predictions(eng, ctx, json_body, c.out, false);
          }
        } else {
          handle_predictions(eng, ctx, body, c.out, binary);
        }
      }
    } else if (path == "/api/v0.1/feedback" || path == "/api/v1.0/feedback") {
      // reward feedback (reference: RestClientController.java:244-291).
      // Builtin units are stateless (the reference's hardcoded units ignore
      // feedback too; bandit learning lives in router microservices), so
      // the walk reduces to acknowledging with a conforming SeldonMessage
      // and counting the reward like the Python engine's metrics do.
      if (eng.paused.load(std::memory_order_relaxed)) {
        if (binary) http_response(c.out, 503, proto_error_bytes(503, "paused"), "application/x-protobuf");
        else http_response(c.out, 503, error_json(503, "paused"));
      } else {
        // feedback counts toward /inflight so rolling-update drain sees it,
        // matching the Python engine (graph/service.py send_feedback gauge)
        InflightGuard guard(eng.inflight);
        double reward = 0.0;
        if (binary) {
          seldontpu::Feedback fb;
          if (!fb.ParseFromArray(body.data(), int(body.size()))) {
            eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
            http_response(c.out, 400, proto_error_bytes(400, "invalid protobuf body"), "application/x-protobuf");
            goto feedback_done;
          }
          reward = fb.reward();
        } else {
          json::Parser parser(body);
          json::Value fb = parser.parse();
          if (!parser.ok || fb.type != json::Value::Obj) {
            eng.metrics.errors.fetch_add(1, std::memory_order_relaxed);
            http_response(c.out, 400, error_json(400, "invalid JSON body"));
            goto feedback_done;
          }
          if (auto* r = fb.find("reward")) reward = r->num;
        }
        eng.metrics.feedback.fetch_add(1, std::memory_order_relaxed);
        if (binary) {
          seldontpu::SeldonMessage resp;
          auto* st = resp.mutable_status();
          st->set_code(200);
          google::protobuf::Value rv;
          rv.set_number_value(reward);
          (*resp.mutable_meta()->mutable_tags())["reward"] = rv;
          std::string bytes;
          resp.SerializeToString(&bytes);
          http_response(c.out, 200, bytes, "application/x-protobuf");
        } else {
          char buf[128];
          snprintf(buf, sizeof buf,
                   "{\"status\":{\"code\":200,\"status\":\"SUCCESS\"},"
                   "\"meta\":{\"tags\":{\"reward\":%g}}}", reward);
          http_response(c.out, 200, buf);
        }
      }
      feedback_done:;
    } else if (path == "/ping") {
      http_response(c.out, 200, "pong", "text/plain");
    } else if (path == "/live") {
      http_response(c.out, 200, "{\"status\":\"ok\"}");
    } else if (path == "/ready") {
      if (eng.paused.load() || !eng.graph_ready.load())
        http_response(c.out, 503, error_json(503, "not ready"));
      else http_response(c.out, 200, "{\"status\":\"ok\"}");
    } else if (path == "/pause") {
      eng.paused.store(true);
      http_response(c.out, 200, "{\"status\":\"paused\"}");
    } else if (path == "/unpause") {
      eng.paused.store(false);
      http_response(c.out, 200, "{\"status\":\"ok\"}");
    } else if (path == "/inflight") {
      http_response(c.out, 200,
                    "{\"inflight\":" + std::to_string(eng.inflight.load()) +
                        ",\"paused\":" + (eng.paused.load() ? "true" : "false") + "}");
    } else if (path == "/metrics" || path == "/prometheus") {
      http_response(c.out, 200, prometheus_text(eng), "text/plain; version=0.0.4");
    } else if (binary) {
      http_response(c.out, 404, proto_error_bytes(404, "no route " + path), "application/x-protobuf");
    } else {
      http_response(c.out, 404, error_json(404, "no route " + path));
    }
  }
}

static int make_listener(int port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof addr) != 0) { close(fd); return -1; }
  if (listen(fd, 1024) != 0) { close(fd); return -1; }
  return fd;
}

static void event_loop(Engine* eng, int listen_fd, unsigned seed) {
  int ep = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = listen_fd;
  epoll_ctl(ep, EPOLL_CTL_ADD, listen_fd, &ev);
  std::map<int, Conn> conns;
  std::mt19937 rng(seed);
  std::map<std::string, UpstreamConn> upstreams;
  std::vector<epoll_event> events(256);
  char buf[65536];

  while (!eng->stopping.load(std::memory_order_relaxed)) {
    int n = epoll_wait(ep, events.data(), events.size(), 100);
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      if (fd == listen_fd) {
        for (;;) {
          int cfd = accept4(listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &cev);
          conns[cfd].fd = cfd;
        }
        continue;
      }
      auto it = conns.find(fd);
      if (it == conns.end()) continue;
      Conn& c = it->second;
      bool closing = false;
      if (events[i].events & (EPOLLERR | EPOLLHUP)) {
        close(fd);
        conns.erase(it);
        continue;
      }
      if (events[i].events & EPOLLIN) {
        for (;;) {
          ssize_t r = read(fd, buf, sizeof buf);
          if (r > 0) {
            if (c.draining || c.close_after_flush) {
              // terminal-error connection: discard the rest of the request
              // instead of buffering it (and NEVER re-parse — the 413/400
              // left the offending request unconsumed in c.in)
              c.drained += (size_t)r;
              // generous cap: the 1s drain_deadline is the real bound;
              // a small byte cap would RST fast senders mid-upload and
              // destroy the error response we just queued
              if (c.drained > (256u << 20)) { closing = true; break; }
            } else {
              c.in.append(buf, r);
            }
          }
          else if (r == 0) { closing = true; break; }
          else {
            if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) closing = true;
            break;
          }
        }
        if (!closing && !c.draining && !c.close_after_flush &&
            !process_buffer(*eng, c, rng, upstreams)) c.close_after_flush = true;
      }
      // flush output; on short write, arm EPOLLOUT so the kernel wakes us
      // when the send buffer drains (a waiting HTTP client sends nothing
      // more, so EPOLLIN alone would stall the response forever)
      while (c.out_off < c.out.size()) {
        ssize_t w = write(fd, c.out.data() + c.out_off, c.out.size() - c.out_off);
        if (w > 0) c.out_off += w;
        else {
          if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) closing = true;
          break;
        }
      }
      bool flushed = c.out_off >= c.out.size();
      if (flushed) { c.out.clear(); c.out_off = 0; }
      bool need_out = !flushed && !closing;
      if (need_out != c.want_epollout) {
        c.want_epollout = need_out;
        epoll_event mev{};
        mev.events = EPOLLIN | (need_out ? EPOLLOUT : 0);
        mev.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &mev);
      }
      if (closing) {
        close(fd);
        conns.erase(it);
      } else if (flushed && c.close_after_flush && !c.draining) {
        shutdown(fd, SHUT_WR);
        c.draining = true;
        c.drain_deadline = std::chrono::steady_clock::now() + std::chrono::seconds(1);
      }
    }
    // reap draining conns whose peer never sent FIN (rare; bounded scan)
    for (auto it2 = conns.begin(); it2 != conns.end();) {
      if (it2->second.draining &&
          std::chrono::steady_clock::now() >= it2->second.drain_deadline) {
        close(it2->first);
        it2 = conns.erase(it2);
      } else {
        ++it2;
      }
    }
  }
  for (auto& kv : conns) close(kv.first);
  for (auto& kv : upstreams)
    if (kv.second.fd >= 0) close(kv.second.fd);
  close(ep);
}

static void engine_stop(Engine* eng);

#include "grpc_front.inc"

// probe one unit endpoint. REST units: GET /ready, any HTTP 2xx = ready
// (the probe the wire contract guarantees on every component, and the one
// the Python engine's readiness loop uses). gRPC units: a successful TCP
// connect = ready — an h2c server would close on a stray HTTP/1.1 request,
// so the probe stays at the transport level (the Python engine's
// channel_ready() does the same).
static bool ping_endpoint(const std::string& host, int port, bool grpc,
                          int timeout_ms) {
  int fd = connect_to(host, port, timeout_ms);
  if (fd < 0) return false;
  if (grpc) { close(fd); return true; }
  char req[256];
  int n = snprintf(req, sizeof req,
                   "GET /ready HTTP/1.1\r\nHost: %s\r\nConnection: close\r\n\r\n",
                   host.c_str());
  bool ok = false;
  if (write(fd, req, n) == n) {
    // loop reads until the status line is complete — a fragmented first
    // segment must not flap a healthy upstream to 503 for a whole sweep
    char buf[64];
    size_t have = 0;
    while (have < sizeof buf - 1) {
      ssize_t r = read(fd, buf + have, sizeof buf - 1 - have);
      if (r <= 0) break;
      have += size_t(r);
      if (have >= 12) break;  // "HTTP/1.1 2xx"
    }
    if (have >= 12) {
      buf[have] = 0;
      const char* sp = strchr(buf, ' ');
      ok = sp && sp[1] == '2';
    }
  }
  close(fd);
  return ok;
}

static void readiness_loop(Engine* eng) {
  // every 5s (reference: @Scheduled(fixedDelay=5000),
  // SeldonGraphReadyChecker.java:111), responsive to shutdown
  while (!eng->stopping.load(std::memory_order_relaxed)) {
    bool all = true;
    for (auto& ep : eng->remote_endpoints)
      if (!ping_endpoint(ep.host, ep.port, ep.grpc, 1000)) { all = false; break; }
    eng->graph_ready.store(all, std::memory_order_relaxed);
    for (int i = 0; i < 50 && !eng->stopping.load(std::memory_order_relaxed); i++)
      usleep(100 * 1000);
  }
}

static void collect_remote_endpoints(const Unit& u,
                                     std::vector<Engine::RemoteEndpoint>& out) {
  if (u.remote) out.push_back({u.host, u.port, u.grpc_transport});
  for (auto& c : u.children) collect_remote_endpoints(c, out);
}

static Engine* engine_start(const std::string& spec_json, int port, int threads,
                            int grpc_port = 0) {
  json::Parser p(spec_json);
  json::Value spec = p.parse();
  if (!p.ok) return nullptr;
  auto* eng = new Engine();
  eng->max_body_bytes = g_max_body_bytes;
  if (auto* name = spec.find("name")) eng->deployment = name->str;
  if (auto* ann = spec.find("annotations")) {
    if (ann->type == json::Value::Obj) {
      if (auto* mb = ann->find("seldon.io/rest-max-body")) {
        long v = 0;
        if (mb->type == json::Value::Num) v = (long)mb->num;
        else if (mb->type == json::Value::Str) v = atol(mb->str.c_str());
        if (v > 0) eng->max_body_bytes = (size_t)v;
      }
    }
  }
  const json::Value* graph = spec.find("graph");
  if (!graph) { delete eng; return nullptr; }
  eng->root = parse_unit(*graph);
  eng->port = port;
  eng->threads = threads;
  collect_remote_endpoints(eng->root, eng->remote_endpoints);
  if (!eng->remote_endpoints.empty()) {
    // readiness starts FALSE until the first sweep proves the graph up —
    // a probe racing boot must not route traffic at a dead upstream
    eng->graph_ready.store(false);
    eng->ready_thread = std::thread(readiness_loop, eng);
  }
  if (grpc_port > 0) {
    int gfd = make_listener(grpc_port);
    // engine_stop, not delete: the readiness thread may already be running
    // over *eng (raw delete = UAF + std::terminate on the joinable thread)
    if (gfd < 0) { engine_stop(eng); return nullptr; }
    eng->listen_fds.push_back(gfd);
    eng->loops.emplace_back(grpc_loop, eng, gfd, 4242u);
  }
  for (int t = 0; t < threads; t++) {
    int lfd = make_listener(port);
    if (lfd < 0) {
      // unwind: already-spawned loops still reference *eng — stop and join
      // them before freeing (a raw delete here would UAF + std::terminate)
      engine_stop(eng);
      return nullptr;
    }
    eng->listen_fds.push_back(lfd);
    eng->loops.emplace_back(event_loop, eng, lfd, 1337u + t);
  }
  return eng;
}

static void engine_stop(Engine* eng) {
  eng->stopping.store(true);
  for (auto& t : eng->loops) t.join();
  if (eng->ready_thread.joinable()) eng->ready_thread.join();
  for (int fd : eng->listen_fds) close(fd);
  delete eng;
}

// ---------------------------------------------------------------------------
// C ABI (ctypes)
// ---------------------------------------------------------------------------

extern "C" {

void* sce_start(const char* spec_json, int port, int threads) {
  signal(SIGPIPE, SIG_IGN);
  return engine_start(spec_json, port, threads <= 0 ? 1 : threads);
}

void* sce_start_grpc(const char* spec_json, int port, int grpc_port, int threads) {
  signal(SIGPIPE, SIG_IGN);
  return engine_start(spec_json, port, threads <= 0 ? 1 : threads, grpc_port);
}

void sce_stop(void* handle) {
  if (handle) engine_stop(static_cast<Engine*>(handle));
}

const char* sce_version() { return "seldon-tpu-engine/0.1.0"; }

}  // extern "C"

// ---------------------------------------------------------------------------
// Standalone binary: serve or bench
// ---------------------------------------------------------------------------

#ifndef SCE_SHARED_ONLY

struct BenchClient {
  int fd = -1;
  std::string out;
  size_t out_off = 0;
  std::string in;
  uint64_t inflight = 0;
  std::chrono::steady_clock::time_point sent_at;
};

// loopback load generator: C concurrent keep-alive connections, one
// outstanding request each (closed-loop, like locust users)
static void run_bench(int port, int clients, double seconds, const std::string& payload,
                      const char* ctype = "application/json") {
  std::string request;
  {
    char head[256];
    int n = snprintf(head, sizeof head,
                     "POST /api/v0.1/predictions HTTP/1.1\r\nHost: 127.0.0.1\r\nContent-Type: %s\r\nContent-Length: %zu\r\n\r\n",
                     ctype, payload.size());
    request.assign(head, n);
    request += payload;
  }
  int ep = epoll_create1(0);
  std::map<int, BenchClient> conns;
  for (int i = 0; i < clients; i++) {
    int fd = connect_to("127.0.0.1", port, upstream_timeout_ms());
    if (fd < 0) { fprintf(stderr, "bench: connect failed\n"); exit(1); }
    fcntl(fd, F_SETFL, O_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.fd = fd;
    epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev);
    BenchClient& c = conns[fd];
    c.fd = fd;
    c.out = request;
    c.sent_at = std::chrono::steady_clock::now();
  }
  uint64_t done = 0, errors = 0;
  std::vector<uint64_t> lat_us;
  lat_us.reserve(1 << 20);
  auto t_start = std::chrono::steady_clock::now();
  auto deadline = t_start + std::chrono::duration<double>(seconds);
  std::vector<epoll_event> events(256);
  char buf[65536];
  while (std::chrono::steady_clock::now() < deadline) {
    int n = epoll_wait(ep, events.data(), events.size(), 50);
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      BenchClient& c = conns[fd];
      if (events[i].events & EPOLLOUT) {
        while (c.out_off < c.out.size()) {
          ssize_t w = write(fd, c.out.data() + c.out_off, c.out.size() - c.out_off);
          if (w > 0) c.out_off += w;
          else break;
        }
        if (c.out_off >= c.out.size()) {
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = fd;
          epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
        }
      }
      if (events[i].events & EPOLLIN) {
        for (;;) {
          ssize_t r = read(fd, buf, sizeof buf);
          if (r > 0) c.in.append(buf, r);
          else break;
        }
        // complete response?
        size_t he = c.in.find("\r\n\r\n");
        if (he != std::string::npos) {
          const char* cl = strcasestr(c.in.c_str(), "content-length:");
          size_t len = cl ? strtoul(cl + 15, nullptr, 10) : 0;
          if (c.in.size() >= he + 4 + len) {
            int status = atoi(c.in.c_str() + 9);
            if (status != 200) errors++;
            auto now = std::chrono::steady_clock::now();
            lat_us.push_back(std::chrono::duration_cast<std::chrono::microseconds>(now - c.sent_at).count());
            done++;
            c.in.erase(0, he + 4 + len);
            // fire next request
            c.out = request;
            c.out_off = 0;
            c.sent_at = now;
            epoll_event ev{};
            ev.events = EPOLLIN | EPOLLOUT;
            ev.data.fd = fd;
            epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
          }
        }
      }
    }
  }
  double elapsed = std::chrono::duration<double>(std::chrono::steady_clock::now() - t_start).count();
  std::sort(lat_us.begin(), lat_us.end());
  auto pct = [&](double q) -> double {
    if (lat_us.empty()) return 0;
    size_t idx = std::min(lat_us.size() - 1, size_t(q * lat_us.size()));
    return lat_us[idx] / 1000.0;  // ms
  };
  printf("{\"requests\": %llu, \"errors\": %llu, \"seconds\": %.3f, \"rps\": %.2f, "
         "\"p50_ms\": %.3f, \"p90_ms\": %.3f, \"p99_ms\": %.3f}\n",
         (unsigned long long)done, (unsigned long long)errors, elapsed, done / elapsed,
         pct(0.50), pct(0.90), pct(0.99));
  for (auto& kv : conns) close(kv.first);
  close(ep);
}

int main(int argc, char** argv) {
  signal(SIGPIPE, SIG_IGN);
  std::string spec_json = R"({"name":"bench","graph":{"name":"stub","implementation":"SIMPLE_MODEL"}})";
  int port = 8000;
  int grpc_port = 0;
  int threads = 1;
  bool bench = false;
  bool bench_binary = false;
  bool bench_grpc = false;
  int clients = 16;
  double seconds = 5.0;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> const char* { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--spec-file") {
      FILE* f = fopen(next(), "rb");
      if (!f) { fprintf(stderr, "cannot open spec file\n"); return 1; }
      spec_json.clear();
      char buf[4096];
      size_t r;
      while ((r = fread(buf, 1, sizeof buf, f)) > 0) spec_json.append(buf, r);
      fclose(f);
    } else if (a == "--spec") spec_json = next();
    else if (a == "--port") port = atoi(next());
    else if (a == "--grpc-port") grpc_port = atoi(next());
    else if (a == "--threads") threads = atoi(next());
    else if (a == "--bench") bench = true;
    else if (a == "--bench-binary") { bench = true; bench_binary = true; }
    else if (a == "--bench-grpc") { bench = true; bench_grpc = true; }
    else if (a == "--clients") clients = atoi(next());
    else if (a == "--seconds") seconds = atof(next());
    else { fprintf(stderr, "unknown arg %s\n", a.c_str()); return 1; }
  }
  Engine* eng = engine_start(spec_json, port, threads, grpc_port);
  if (!eng) { fprintf(stderr, "bad spec\n"); return 1; }
  fprintf(stderr, "seldon-tpu-engine listening on :%d (%d threads)\n", port, threads);
  if (bench) {
    // ONE payload for both binary tiers so REST-binary and gRPC numbers
    // measure the identical request shape
    auto bench_payload = [] {
      seldontpu::SeldonMessage m;
      auto* pd = m.mutable_data();
      for (const char* n : {"a", "b", "c", "d", "e"}) pd->add_names(n);
      auto* raw = pd->mutable_raw();
      raw->set_dtype("float32");
      raw->add_shape(1);
      raw->add_shape(5);
      float vals[5] = {1, 2, 3, 4, 5};
      raw->set_data(std::string(reinterpret_cast<const char*>(vals), sizeof vals));
      std::string payload;
      m.SerializeToString(&payload);
      return payload;
    };
    if (bench_grpc) {
      if (grpc_port <= 0) { fprintf(stderr, "--bench-grpc needs --grpc-port\n"); return 1; }
      run_grpc_bench(grpc_port, clients, seconds, bench_payload());
    } else if (bench_binary) {
      // protobuf front: raw float32 tensor, no JSON/base64 anywhere
      run_bench(port, clients, seconds, bench_payload(), "application/x-protobuf");
    } else {
      // payload mirrors the reference benchmark notebook's request
      std::string payload = R"({"data":{"names":["a","b","c","d","e"],"tensor":{"shape":[1,5],"values":[1.0,2.0,3.0,4.0,5.0]}}})";
      run_bench(port, clients, seconds, payload);
    }
    engine_stop(eng);
    return 0;
  }
  for (;;) pause();
  return 0;
}

#endif  // SCE_SHARED_ONLY
