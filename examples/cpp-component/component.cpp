// Example NON-PYTHON graph component speaking the Seldon wire contract.
//
// Counterpart of the reference's Java s2i example handler
// (reference: wrappers/s2i/java/, ExampleModelHandler.java; R/NodeJS
// wrappers doc/source/{R,nodejs}/) — proof that a component in any
// language can sit behind the engine: it only has to answer the wrapper
// route set with SeldonMessage JSON bodies.
//
// This one is a ~250-line dependency-free C++17 REST microservice:
//   POST /predict          JSON SeldonMessage in -> row means out
//   POST /transform-input  passthrough with a tag
//   GET  /ping /ready /live
//
// Build + run:
//   g++ -O2 -std=c++17 -o component component.cpp
//   ./component 9100
//
// Put it in a graph like any wrapped model:
//   {"name": "cpp", "type": "MODEL",
//    "endpoint": {"service_host": "127.0.0.1", "service_port": 9100,
//                 "transport": "REST"}}
//
// tests/test_cpp_component_example.py builds it and fronts it with BOTH
// engines (Python + native).

#include <arpa/inet.h>
#include <csignal>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// -- tiny JSON helpers (enough for {"data": {"ndarray": [[...]]}}) ----------

// find the first top-level ndarray matrix in the body; returns rows
static bool parse_ndarray(const std::string& body,
                          std::vector<std::vector<double>>& rows) {
  size_t p = body.find("\"ndarray\"");
  if (p == std::string::npos) return false;
  p = body.find('[', p);
  if (p == std::string::npos) return false;
  int depth = 0;
  std::vector<double> cur;
  std::string num;
  auto flush_num = [&]() {
    if (!num.empty()) {
      cur.push_back(strtod(num.c_str(), nullptr));
      num.clear();
    }
  };
  for (size_t i = p; i < body.size(); i++) {
    char c = body[i];
    if (c == '[') {
      depth++;
      if (depth == 2) cur.clear();
    } else if (c == ']') {
      flush_num();
      if (depth == 2) rows.push_back(cur);
      depth--;
      if (depth == 0) return !rows.empty();
    } else if (c == ',') {
      flush_num();
    } else if (isdigit(c) || c == '-' || c == '+' || c == '.' || c == 'e' ||
               c == 'E') {
      num.push_back(c);
    }
  }
  return false;
}

static std::string mean_response(const std::vector<std::vector<double>>& rows) {
  std::string out = "{\"data\":{\"names\":[\"mean\"],\"ndarray\":[";
  char buf[64];
  for (size_t r = 0; r < rows.size(); r++) {
    double sum = 0;
    for (double v : rows[r]) sum += v;
    double mean = rows[r].empty() ? 0.0 : sum / double(rows[r].size());
    snprintf(buf, sizeof buf, "%s[%.12g]", r ? "," : "", mean);
    out += buf;
  }
  out += "]},\"meta\":{\"tags\":{\"component\":\"cpp-example\"}}}";
  return out;
}

// -- minimal HTTP/1.1 serving ----------------------------------------------

static void respond(int fd, int status, const std::string& body,
                    bool keep_alive) {
  const char* reason = status == 200 ? "OK" : status == 400 ? "Bad Request"
                                                            : "Not Found";
  char head[256];
  int n = snprintf(head, sizeof head,
                   "HTTP/1.1 %d %s\r\nContent-Type: application/json\r\n"
                   "Content-Length: %zu\r\nConnection: %s\r\n\r\n",
                   status, reason, body.size(),
                   keep_alive ? "keep-alive" : "close");
  std::string resp(head, n);
  resp += body;
  size_t off = 0;
  while (off < resp.size()) {
    ssize_t w = write(fd, resp.data() + off, resp.size() - off);
    if (w <= 0) return;
    off += size_t(w);
  }
}

static void serve_conn(int fd) {
  std::string buf;
  char tmp[65536];
  for (;;) {
    size_t hdr_end;
    while ((hdr_end = buf.find("\r\n\r\n")) == std::string::npos) {
      ssize_t r = read(fd, tmp, sizeof tmp);
      if (r <= 0) return;
      buf.append(tmp, r);
    }
    std::string head = buf.substr(0, hdr_end);
    size_t clen = 0;
    {
      size_t cp = head.find("Content-Length:");
      if (cp == std::string::npos) cp = head.find("content-length:");
      if (cp != std::string::npos) clen = strtoul(head.c_str() + cp + 15, nullptr, 10);
    }
    while (buf.size() < hdr_end + 4 + clen) {
      ssize_t r = read(fd, tmp, sizeof tmp);
      if (r <= 0) return;
      buf.append(tmp, r);
    }
    std::string body = buf.substr(hdr_end + 4, clen);
    buf.erase(0, hdr_end + 4 + clen);

    size_t sp1 = head.find(' ');
    size_t sp2 = head.find(' ', sp1 + 1);
    if (sp1 == std::string::npos || sp2 == std::string::npos) return;
    std::string method = head.substr(0, sp1);
    std::string path = head.substr(sp1 + 1, sp2 - sp1 - 1);
    size_t q = path.find('?');
    if (q != std::string::npos) path.resize(q);

    if (path == "/ping") {
      respond(fd, 200, "\"pong\"", true);
    } else if (path == "/ready" || path == "/live" || path == "/health/status") {
      respond(fd, 200, "{\"status\":\"ok\"}", true);
    } else if (path == "/predict" || path == "/api/v0.1/predictions") {
      std::vector<std::vector<double>> rows;
      if (!parse_ndarray(body, rows)) {
        respond(fd, 400,
                "{\"status\":{\"code\":400,\"info\":\"need data.ndarray\","
                "\"status\":\"FAILURE\"}}",
                true);
      } else {
        respond(fd, 200, mean_response(rows), true);
      }
    } else if (path == "/transform-input") {
      // passthrough transformer: the body goes back with a tag merged in
      std::string out = body;
      size_t mp = out.rfind('}');
      if (mp != std::string::npos)
        out.insert(mp, ",\"meta\":{\"tags\":{\"transformed-by\":\"cpp-example\"}}");
      respond(fd, 200, out, true);
    } else {
      respond(fd, 404,
              "{\"status\":{\"code\":404,\"info\":\"no route\","
              "\"status\":\"FAILURE\"}}",
              true);
    }
  }
}

int main(int argc, char** argv) {
  signal(SIGCHLD, SIG_IGN);  // no zombies from the per-connection forks
  int port = argc > 1 ? atoi(argv[1]) : 9100;
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) < 0 || listen(lfd, 64) < 0) {
    perror("bind/listen");
    return 1;
  }
  fprintf(stderr, "cpp-example component listening on :%d\n", port);
  for (;;) {
    int fd = accept(lfd, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    if (fork() == 0) {  // process-per-connection: simplest correct model
      close(lfd);
      serve_conn(fd);
      _exit(0);
    }
    close(fd);
  }
}
