#!/usr/bin/env bash
# Release smoke: boot the full single-host stack and drive every external
# surface once (counterpart of the reference's testing/scripts e2e tier,
# minus the kind cluster). Exits non-zero on the first failed check.
#
#   JAX_PLATFORMS=cpu bash deploy/smoke.sh
#
set -euo pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH="${PYTHONPATH:-$PWD}"
WORK=$(mktemp -d)
trap 'kill $(jobs -p) 2>/dev/null || true; rm -rf "$WORK"' EXIT

say() { printf '\n== %s\n' "$*"; }

# --- model + graph ---------------------------------------------------------
mkdir -p "$WORK/model"
cat > "$WORK/model/jax_config.json" <<'EOF'
{"family": "llm", "config": {"vocab_size": 256, "d_model": 64, "n_layers": 2,
 "n_heads": 4, "n_kv_heads": 2, "d_ff": 128, "max_seq": 64, "dtype": "float32"}}
EOF
cat > "$WORK/graph.json" <<EOF
{"name": "smoke", "graph": {"name": "llm", "type": "MODEL",
  "implementation": "GENERATE_SERVER", "modelUri": "$WORK/model",
  "parameters": [{"name": "slots", "type": "INT", "value": "2"},
                 {"name": "steps_per_poll", "type": "INT", "value": "4"}]}}
EOF

PORT=${SMOKE_PORT:-9971}
LOGPORT=$((PORT + 1))

say "request-logger on :$LOGPORT"
python -m seldon_core_tpu.request_logging --port "$LOGPORT" >"$WORK/logger.log" 2>&1 &

say "engine on :$PORT"
SELDON_MESSAGE_LOGGING_SERVICE="http://127.0.0.1:$LOGPORT/" \
python -m seldon_core_tpu.engine_main --spec "$WORK/graph.json" \
    --http-port "$PORT" >"$WORK/engine.log" 2>&1 &

for i in $(seq 1 120); do
  curl -fsS "http://127.0.0.1:$PORT/ready" >/dev/null 2>&1 && break
  sleep 0.5
  [ "$i" = 120 ] && { echo "engine never became ready"; cat "$WORK/engine.log"; exit 1; }
done

say "unary generate"
OUT=$(curl -fsS -X POST "http://127.0.0.1:$PORT/api/v0.1/predictions" \
  -H 'Content-Type: application/json' \
  -d '{"jsonData": {"prompt_tokens": [[5, 17, 42]], "max_new_tokens": 6}}')
echo "$OUT" | python -c 'import json,sys; t=json.load(sys.stdin)["jsonData"]["tokens"][0]; assert t[:3]==[5,17,42] and len(t)==9, t; print("tokens:", t)'

say "SSE stream"
curl -fsS -N -X POST "http://127.0.0.1:$PORT/api/v0.1/generate" \
  -H 'Content-Type: application/json' \
  -d '{"jsonData": {"prompt_tokens": [[5, 17, 42]], "max_new_tokens": 6}}' \
  | grep -c '^data: ' | xargs -I{} sh -c 'test {} -ge 2 && echo "events: {}"'

say "feedback"
curl -fsS -X POST "http://127.0.0.1:$PORT/api/v0.1/feedback" \
  -H 'Content-Type: application/json' \
  -d '{"reward": 1.0}' >/dev/null && echo ok

say "probes + metrics + openapi + traces"
curl -fsS "http://127.0.0.1:$PORT/ping" >/dev/null && echo ping-ok
curl -fsS "http://127.0.0.1:$PORT/inflight" | grep -q '"inflight"' && echo inflight-ok
curl -fsS "http://127.0.0.1:$PORT/prometheus" | grep -q seldon_api_engine_server_requests && echo metrics-ok
curl -fsS "http://127.0.0.1:$PORT/openapi.json" | grep -q '"/api/v0.1/predictions"' && echo openapi-ok
curl -fsS "http://127.0.0.1:$PORT/traces" >/dev/null && echo traces-ok

say "payload logging reached the collector"
for i in $(seq 1 20); do
  N=$(curl -fsS "http://127.0.0.1:$LOGPORT/entries" | python -c 'import json,sys; print(len(json.load(sys.stdin)))' 2>/dev/null || echo 0)
  [ "$N" -ge 1 ] && { echo "entries: $N"; break; }
  sleep 0.5
  [ "$i" = 20 ] && { echo "no logged pairs"; exit 1; }
done

say "converted-checkpoint export -> serve (convert.py path)"
python - "$WORK" <<'PYEOF'
import sys
from seldon_core_tpu.models.llm import DecoderLM
from seldon_core_tpu.convert import export_model
cfg = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=2, n_kv_heads=2,
           d_ff=64, max_seq=64, dtype="float32")
m = DecoderLM(**cfg)
export_model("llm", cfg, m.init_params(0), sys.argv[1] + "/exported")
print("exported ok")
PYEOF
cat > "$WORK/graph2.json" <<EOF
{"name": "smoke2", "graph": {"name": "llm", "type": "MODEL",
  "implementation": "GENERATE_SERVER", "modelUri": "$WORK/exported",
  "parameters": [{"name": "slots", "type": "INT", "value": "2"}]}}
EOF
PORT2=$((PORT + 2))
python -m seldon_core_tpu.engine_main --spec "$WORK/graph2.json" \
    --http-port "$PORT2" --no-grpc >"$WORK/engine2.log" 2>&1 &
for i in $(seq 1 120); do
  curl -fsS "http://127.0.0.1:$PORT2/ready" >/dev/null 2>&1 && break
  sleep 0.5
  [ "$i" = 120 ] && { echo "exported engine never ready"; cat "$WORK/engine2.log"; exit 1; }
done
OUT=$(curl -fsS -X POST "http://127.0.0.1:$PORT2/api/v0.1/predictions" \
  -H 'Content-Type: application/json' \
  -d '{"jsonData": {"prompt_tokens": [[3, 9]], "max_new_tokens": 4}}')
echo "$OUT" | python -c 'import json,sys; t=json.load(sys.stdin)["jsonData"]["tokens"][0]; assert t[:2]==[3,9] and len(t)==6, t; print("exported-serve tokens:", t)'

say "kubernetes render (sdctl render)"
cat > "$WORK/dep.json" <<K8SEOF
{"name": "smoke-k8s", "predictors": [
  {"name": "main", "replicas": 1, "traffic": 100,
   "tpuMesh": {"model": 4},
   "graph": {"name": "m", "type": "MODEL", "implementation": "JAX_SERVER",
             "modelUri": "$WORK/model"}}]}
K8SEOF
python -m seldon_core_tpu.controlplane render -f "$WORK/dep.json" -o "$WORK/k8s.yaml"
grep -q "kind: Deployment" "$WORK/k8s.yaml" && grep -q "google.com/tpu" "$WORK/k8s.yaml" && echo "render ok"

say "async ingest tier (file queue -> engine -> results sink)"
mkdir -p "$WORK/queue"
python - <<INGEOF
import json
with open("$WORK/recs.jsonl", "w") as f:
    for i in range(6):
        f.write(json.dumps({"id": f"s{i}",
                            "request": {"jsonData": {"prompt_tokens": [[2, 4]],
                                                     "max_new_tokens": 2}}}) + "\n")
INGEOF
python -m seldon_core_tpu.ingest enqueue --queue-dir "$WORK/queue" --file "$WORK/recs.jsonl"
python -m seldon_core_tpu.ingest consume --queue-dir "$WORK/queue" \
  --engine "127.0.0.1:$PORT" --out "$WORK/ingest-results.jsonl" --drain
python - <<INGEOF
from seldon_core_tpu.ingest import read_results
res = read_results("$WORK/ingest-results.jsonl")
assert len(res) == 6, res
print("ingest ok:", sorted(res))
INGEOF

say "SMOKE PASSED"
