#!/usr/bin/env python3
"""CI smoke for the fault-tolerant disaggregated generate path.

Boots a two-listener prefill pool and a decode engine over the chunked
TCP transport with the **SELDON_FAULTS env grammar** driving the chaos:
seeded KV-transport faults on both peers (CRC corruption on one,
connect-refused on the other) plus one induced scheduler poll death on
the decode batcher. Then asserts:

* every greedy response through the chaotic decode engine is
  byte-identical to the fault-free unified server's (failover retries
  and local degradation absorb the faults; a transient 503 with
  Retry-After during the supervised restart is the only tolerated
  non-200, and the retry must succeed);
* the recovery counters are exercised — ``peer_ejections`` from the
  transport faults, ``batcher_restarts`` from the induced poll death,
  and ``degraded_local_prefill`` once both listeners are torn down;
* the ``seldon_engine_batcher_restarts`` / ``seldon_engine_peer_ejections``
  (and ``_degraded_local_prefill`` / ``_batcher_healthy``) series land
  in the Prometheus exposition.

Run directly (``JAX_PLATFORMS=cpu python tools/chaos_smoke.py``) or from
the CI chaos step. Exits non-zero on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runtime thread-role assertions (analysis/roles.py): a scheduler
    # thread violation during chaos recovery fails the smoke loudly
    # instead of corrupting device state (must precede seldon imports)
    os.environ.setdefault("SELDON_DEBUG_THREADS", "1")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.graph.engine_metrics import REGISTRY
    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.serving.disagg import PrefillTransportServer
    from seldon_core_tpu.servers.generateserver import GenerateServer

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory(prefix="chaos-smoke-") as root:
        cfg = {"vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 2,
               "n_kv_heads": 2, "d_ff": 64, "max_seq": 64}
        model_dir = write_model_dir(root, "llm", cfg)
        common = dict(model_uri=model_dir, steps_per_poll=4,
                      warmup_prompt_lens=[4], warmup_max_new_tokens=6)

        # reference + prefill pool load BEFORE the fault env exists:
        # only the decode engine runs chaotic
        unified = GenerateServer(slots=2, **common)
        unified.load()
        pf1 = GenerateServer(role="prefill", **common)
        pf1.load()
        pf2 = GenerateServer(role="prefill", **common)
        pf2.load()
        l1 = PrefillTransportServer(pf1, port=0)
        l2 = PrefillTransportServer(pf2, port=0)

        # the SELDON_FAULTS grammar under test: kv targets per peer +
        # the scheduler-death section (docs/operate.md "Resilience")
        os.environ["SELDON_FAULTS"] = json.dumps({
            "seed": 7,
            "rules": [
                {"unit": f"kv:127.0.0.1:{l1.port}", "kv_corrupt_rate": 0.7},
                {"unit": f"kv:127.0.0.1:{l2.port}",
                 "kv_connect_refused_rate": 0.5},
            ],
            "scheduler": {"die_after_polls": 6, "times": 1},
        })
        try:
            dec = GenerateServer(
                slots=2, role="decode",
                peer=f"127.0.0.1:{l1.port},127.0.0.1:{l2.port}",
                peer_eject_backoff_s=0.2, restart_backoff_s=0.1,
                **common,
            )
            dec.load()
        finally:
            del os.environ["SELDON_FAULTS"]

        uni_h = EngineHarness(unified, name="chaos-unified").start()
        dec_h = EngineHarness(dec, name="chaos-decode").start()
        headers = {"Content-Type": "application/json"}

        def greedy(port: int, prompt, retries: int = 4) -> dict:
            """One greedy request; a 503 (supervised restart in flight)
            must carry Retry-After and succeed on retry."""
            last = None
            for _ in range(retries):
                conn = http.client.HTTPConnection("127.0.0.1", port)
                conn.request("POST", "/api/v0.1/predictions", json.dumps({
                    "jsonData": {"prompt_tokens": [list(prompt)],
                                 "max_new_tokens": 6, "temperature": 0.0},
                }).encode(), headers)
                resp = conn.getresponse()
                payload = resp.read()
                retry_after = resp.getheader("Retry-After")
                conn.close()
                if resp.status == 200:
                    return json.loads(payload)["jsonData"]
                last = (resp.status, retry_after, payload[:120])
                check("503 during restart carries Retry-After",
                      resp.status == 503 and retry_after is not None,
                      f"status={resp.status} retry_after={retry_after}")
                time.sleep(min(2.0, float(retry_after or 1)))
            raise RuntimeError(f"request never succeeded: {last}")

        try:
            prompts = [[5, 6, 7, 8], [9, 10, 11], [1, 2, 3, 4, 5],
                       [7, 7, 7, 7], [2, 4, 6, 8], [11, 12, 13]]
            refs = [greedy(uni_h.http_port, p)["tokens"][0] for p in prompts]

            # drive chaotic traffic until the induced scheduler death has
            # fired and restarted (plus enough transfers to eject peers)
            identical = True
            deadline = time.monotonic() + 60.0
            rounds = 0
            while time.monotonic() < deadline:
                for p, r in zip(prompts, refs):
                    got = greedy(dec_h.http_port, p)["tokens"][0]
                    if got != r:
                        identical = False
                rounds += 1
                if (dec.batcher.stats["batcher_restarts"] >= 1
                        and dec.batcher.stats["peer_ejections"] >= 1
                        and dec.batcher.health == "serving"
                        and rounds >= 2):
                    break
            st = dec.batcher.stats
            check("chaotic greedy responses byte-identical", identical)
            check("peer ejections exercised", st["peer_ejections"] >= 1,
                  f"ejections={st['peer_ejections']}")
            check("induced scheduler death recovered",
                  st["batcher_restarts"] >= 1
                  and dec.batcher.health == "serving",
                  f"restarts={st['batcher_restarts']} "
                  f"health={dec.batcher.health}")

            # full-pool outage: both listeners torn down -> local prefill
            l1.close()
            l2.close()
            time.sleep(0.3)
            for p, r in zip(prompts[:3], refs[:3]):
                got = greedy(dec_h.http_port, p)["tokens"][0]
                check("pool-down greedy identical (local prefill)",
                      got == r, "" if got == r else f"{got} != {r}")
            check("degraded_local_prefill exercised",
                  st["degraded_local_prefill"] >= 1,
                  f"degraded={st['degraded_local_prefill']}")

            # recovery series in the Prometheus exposition
            expo = REGISTRY.expose()
            for series in ("seldon_engine_batcher_restarts",
                           "seldon_engine_peer_ejections",
                           "seldon_engine_degraded_local_prefill",
                           "seldon_engine_batcher_healthy"):
                check(f"exposition has {series}", series in expo)
            check("batcher restart counter counts the death",
                  REGISTRY.counter_total(
                      "seldon_engine_batcher_restarts", {}) >= 1)
            check("peer ejection counter counts the faults",
                  REGISTRY.counter_total(
                      "seldon_engine_peer_ejections", {}) >= 1)

            # -- pressure leg: SELDON_FAULTS pressure grammar shrinks
            # the HBM ledger mid-run -> decode-lane preemption ->
            # byte-identical recompute-resume, then the exposition must
            # carry the seldon_engine_pressure_* / _preemptions series
            long_kw = {"max_new_tokens": 40, "temperature": 0.0}
            long_prompts = prompts[:3]
            long_refs = [
                unified.batcher.generate(list(p), **long_kw)
                for p in long_prompts
            ]
            # ~1.3 lanes of end-of-generation footprint: two live lanes
            # must preempt, one always fits (no livelock)
            kvb = unified.batcher._kv_key_bytes
            shrink_to = int(1.3 * 64 * kvb)
            os.environ["SELDON_FAULTS"] = json.dumps({
                "pressure": {"shrink_to_bytes": shrink_to,
                             "after_polls": 4,
                             "restore_after_polls": 24},
            })
            try:
                prs = GenerateServer(
                    slots=2, hbm_ledger_bytes=1 << 40, **common
                )
                prs.load()
            finally:
                del os.environ["SELDON_FAULTS"]
            prs_h = EngineHarness(prs, name="chaos-pressure").start()
            try:
                futs = [
                    prs.batcher.submit(list(p), **long_kw)
                    for p in long_prompts
                ]
                outs = [f.result(timeout=60) for f in futs]
                st = prs.batcher.stats
                check("pressure shrink preempted a lane",
                      st["preemptions"] >= 1,
                      f"preemptions={st['preemptions']}")
                check("preempted requests resumed byte-identical",
                      outs == long_refs and
                      st["preempt_resumes"] >= 1,
                      f"resumes={st['preempt_resumes']}")
                # one engine-served request flushes the gen_* metrics
                # into the registry so the series land in /metrics
                # (greedy() asks for 6 new tokens — compare like for like)
                short_ref = unified.batcher.generate(
                    list(long_prompts[0]), max_new_tokens=6,
                    temperature=0.0)
                got = greedy(prs_h.http_port, long_prompts[0])
                check("pressure engine path byte-identical",
                      got["tokens"][0] == short_ref)
                expo = REGISTRY.expose()
                for series in ("seldon_engine_preemptions",
                               "seldon_engine_preemption_resumes",
                               "seldon_engine_pressure_used_bytes",
                               "seldon_engine_pressure_budget_bytes",
                               "seldon_engine_pressure_active"):
                    check(f"exposition has {series}", series in expo)
                check("preemption counter counts the reclaim",
                      REGISTRY.counter_total(
                          "seldon_engine_preemptions", {}) >= 1)
            finally:
                prs_h.stop()
                prs.close()

            # -- tier leg: the SAME pressure grammar with the host KV
            # tier on — the preempted lane must resume via host-tier
            # COPY-BACK (kv_tier_hits > 0, the replay-fallback counter
            # quiet) with greedy AND seeded output byte-identical to
            # tier-off, a demoted prefix must promote back on resume of
            # traffic, and the seldon_engine_kv_tier_* series must land
            # in the exposition
            tier_kw = {"max_new_tokens": 40, "temperature": 0.0}
            seeded_kw = {"max_new_tokens": 30, "temperature": 0.8,
                         "seed": 9}
            tier_prompts = prompts[:3]
            tier_refs = [
                unified.batcher.generate(list(p), **tier_kw)
                for p in tier_prompts
            ]
            seeded_refs = [
                unified.batcher.generate(list(p), **seeded_kw)
                for p in tier_prompts
            ]
            os.environ["SELDON_FAULTS"] = json.dumps({
                "pressure": {"shrink_to_bytes": shrink_to,
                             "after_polls": 4,
                             "restore_after_polls": 24},
            })
            try:
                tsv = GenerateServer(
                    slots=2, hbm_ledger_bytes=1 << 40,
                    host_kv_tier_bytes=64 << 20, kv_tier_min_tokens=2,
                    prefix_cache_hbm_bytes=1 << 20,
                    prefix_cache_min_tokens=4, **common,
                )
                tsv.load()
            finally:
                del os.environ["SELDON_FAULTS"]
            tsv_h = EngineHarness(tsv, name="chaos-kvtier").start()
            try:
                futs = [
                    tsv.batcher.submit(list(p), **tier_kw)
                    for p in tier_prompts
                ]
                outs = [f.result(timeout=60) for f in futs]
                tb = tsv.batcher
                tb.sync_kv_tier_stats()
                st = tb.stats
                check("tier leg preempted a lane", st["preemptions"] >= 1,
                      f"preemptions={st['preemptions']}")
                check("tier copy-back resume exercised",
                      st["kv_tier_hits"] >= 1,
                      f"hits={st['kv_tier_hits']}")
                check("tier replay-fallback counter quiet",
                      st["kv_tier_replay_fallbacks"] == 0,
                      f"fallbacks={st['kv_tier_replay_fallbacks']}")
                check("tier greedy resume byte-identical",
                      outs == tier_refs)
                # seeded window: arm a second shrink through the hook
                from seldon_core_tpu.resilience.faults import FaultInjector
                inj = FaultInjector([], pressure={
                    "shrink_to_bytes": shrink_to,
                    "after_polls": tb._work_poll_count + 2,
                    "restore_after_polls": 24,
                })
                tb.pressure_hook = inj.pressure_hook()
                sfuts = [
                    tb.submit(list(p), **seeded_kw) for p in tier_prompts
                ]
                souts = [f.result(timeout=60) for f in sfuts]
                check("tier seeded resume byte-identical",
                      souts == seeded_refs)
                tb.sync_kv_tier_stats()
                check("tier demotions recorded",
                      st["kv_tier_demotions"] >= 1,
                      f"demotions={st['kv_tier_demotions']}")
                # one engine-served request flushes the gen_kv_tier_*
                # deltas into the registry
                short_ref2 = unified.batcher.generate(
                    list(tier_prompts[0]), max_new_tokens=6,
                    temperature=0.0)
                got = greedy(tsv_h.http_port, tier_prompts[0])
                check("tier engine path byte-identical",
                      got["tokens"][0] == short_ref2)
                expo = REGISTRY.expose()
                for series in ("seldon_engine_kv_tier_demotions",
                               "seldon_engine_kv_tier_promotions",
                               "seldon_engine_kv_tier_hits",
                               "seldon_engine_kv_tier_evictions",
                               "seldon_engine_kv_tier_replay_fallbacks",
                               "seldon_engine_kv_tier_bytes"):
                    check(f"exposition has {series}", series in expo)
                check("tier hit counter counts the copy-backs",
                      REGISTRY.counter_total(
                          "seldon_engine_kv_tier_hits", {}) >= 1)
                check("tier replay-fallback series quiet",
                      REGISTRY.counter_total(
                          "seldon_engine_kv_tier_replay_fallbacks",
                          {}) == 0)
            finally:
                tsv_h.stop()
                tsv.close()

            # -- migration leg: graceful drain over TCP (POST /drain),
            # then a decode member killed MID-STREAM with the client
            # resuming on the peer from the span's SGC1 resume token —
            # byte-identical total output, no span re-sent, and the
            # seldon_engine_drains/migrations series in the exposition
            pf3 = GenerateServer(role="prefill", **common)
            pf3.load()
            l3 = PrefillTransportServer(pf3, port=0)
            mig_kw = dict(common, steps_per_poll=1)
            dA = GenerateServer(  # the member that will be killed
                slots=2, role="decode", peer=f"127.0.0.1:{l3.port}",
                resume_tokens=1, restart_budget=0, **mig_kw,
            )
            dA.load()
            dB = GenerateServer(  # the kill's resume target
                slots=2, role="decode", peer=f"127.0.0.1:{l3.port}",
                resume_tokens=1, **mig_kw,
            )
            dB.load()
            dC = GenerateServer(  # the drain's handoff target
                slots=2, role="decode", peer=f"127.0.0.1:{l3.port}",
                resume_tokens=1, **mig_kw,
            )
            dC.load()
            a_h = EngineHarness(dA, name="mig-kill").start()
            b_h = EngineHarness(dB, name="mig-resume").start()
            c_h = EngineHarness(dC, name="mig-drain-dst").start()
            mig_prompt = [3, 1, 4, 1]
            mig_gen = dict(max_new_tokens=56, temperature=0.8, seed=5)
            mig_ref = unified.batcher.generate(
                list(mig_prompt), eos_id=None, **mig_gen,
            )

            def sse_events(resp, stop_after=None, on_first=None):
                """Parse `data: {...}` events off a live SSE response;
                optionally fire a callback after the first span."""
                events = []
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    if not line.startswith(b"data: "):
                        continue
                    ev = json.loads(line[6:])
                    events.append(ev)
                    if on_first is not None and len(events) == 1:
                        on_first()
                        on_first = None
                    if ev.get("done") or (
                        stop_after is not None and len(events) >= stop_after
                    ):
                        break
                return events

            try:
                # (1) graceful drain over TCP: a stream in flight on dB,
                # POST /drain hands its checkpoint to dC's engine
                conn = http.client.HTTPConnection(
                    "127.0.0.1", b_h.http_port, timeout=60)
                conn.request("POST", "/api/v0.1/generate", json.dumps({
                    "jsonData": {"prompt_tokens": mig_prompt, **mig_gen},
                }).encode(), headers)
                stream_resp = conn.getresponse()
                first_ev = sse_events(stream_resp, stop_after=1)[0]
                drained_spans = list(first_ev["tokens"])
                dconn = http.client.HTTPConnection(
                    "127.0.0.1", b_h.http_port, timeout=60)
                dconn.request("POST", "/drain", json.dumps({
                    "to": f"127.0.0.1:{c_h.http_port}",
                }).encode(), headers)
                dresp = dconn.getresponse()
                dout = json.loads(dresp.read())
                dconn.close()
                check("TCP drain route answers 200", dresp.status == 200,
                      str(dout)[:120])
                # the ORIGINAL stream keeps delivering through the drain
                tail = sse_events(stream_resp)
                conn.close()
                for ev in tail:
                    if not ev.get("done"):
                        drained_spans.extend(ev["tokens"])
                final = next((e for e in tail if e.get("done")), {})
                check("drained stream completes byte-identical",
                      final.get("tokens") == mig_ref)
                check("drained stream re-sends no span",
                      drained_spans == mig_ref[len(mig_prompt):])
                check("draining member refuses new work",
                      dB.batcher.health == "draining")

                # (2) member kill mid-stream: dA dies after the first
                # span; the resume token continues on dB's peer engine
                conn = http.client.HTTPConnection(
                    "127.0.0.1", a_h.http_port, timeout=60)
                conn.request("POST", "/api/v0.1/generate", json.dumps({
                    "jsonData": {"prompt_tokens": mig_prompt, **mig_gen},
                }).encode(), headers)
                resp = conn.getresponse()

                def kill():
                    def die(_n):
                        raise RuntimeError("chaos: injected member kill")
                    dA.batcher.fault_hook = die

                events = []
                try:
                    events = sse_events(resp, on_first=kill)
                except Exception:  # noqa: BLE001 - severed mid-stream
                    pass
                conn.close()
                delivered, token = [], None
                for ev in events:
                    if ev.get("done"):
                        break
                    delivered.extend(ev["tokens"])
                    token = ev.get("resume_token", token)
                check("killed stream delivered spans with resume tokens",
                      bool(delivered) and token is not None)
                check("member latched dead after kill",
                      dA.batcher.health == "dead")
                rconn = http.client.HTTPConnection(
                    "127.0.0.1", c_h.http_port, timeout=60)
                rconn.request("POST", "/api/v0.1/generate", json.dumps({
                    "jsonData": {"resume_token": token},
                }).encode(), headers)
                r_events = sse_events(rconn.getresponse())
                rconn.close()
                resumed = []
                r_final = {}
                for ev in r_events:
                    if ev.get("done"):
                        r_final = ev
                        break
                    resumed.extend(ev["tokens"])
                check("kill resumed byte-identical on the peer",
                      r_final.get("tokens") == mig_ref)
                check("kill resume re-sends no span",
                      delivered + resumed == mig_ref[len(mig_prompt):],
                      f"{len(delivered)}+{len(resumed)} vs "
                      f"{len(mig_ref) - len(mig_prompt)}")

                expo = REGISTRY.expose()
                for series in ("seldon_engine_drains_total",
                               "seldon_engine_migrations_total",
                               "seldon_engine_migrations_resumed",
                               "seldon_engine_checkpoint_exports"):
                    check(f"exposition has {series}", series in expo)
                check("drain counter counts the drain",
                      REGISTRY.counter_total(
                          "seldon_engine_drains_total", {}) >= 1)
            finally:
                for hh in (a_h, b_h, c_h):
                    hh.stop()
                l3.close()
                for c in (pf3, dA, dB, dC):
                    c.close()
        finally:
            uni_h.stop()
            dec_h.stop()
            for listener in (l1, l2):
                listener.close()
            for c in (unified, pf1, pf2, dec):
                c.close()

    if failures:
        print(f"\nchaos smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nchaos smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
