#!/usr/bin/env python3
"""CI smoke for multi-tenant multi-model serving (generate.md §13).

Boots TWO tenants — distinct checkpoints, distinct SLO classes — on ONE
GenerateServer behind a real engine on sockets, plus a dedicated
single-tenant server per checkpoint as the identity reference, then
asserts:

* interleaved per-tenant traffic routed by the ``Seldon-Tenant`` header
  is byte-identical (greedy AND seeded sampling) to each tenant's
  dedicated server — every interleave step forces a demote→promote
  cycle of the other tenant, so the identity holds ACROSS weight paging;
* the pager actually paged (page-ins / switches counted) and a
  scale-to-zero tenant comes back without recompiling (jit cache sizes
  pinned across the cycle);
* an undeclared tenant is refused typed, not served the wrong weights;
* the ``seldon_engine_tenant_*`` + ``seldon_engine_weight_page*`` /
  ``seldon_engine_weight_pager_*`` series land in the Prometheus
  exposition, per-tenant series carrying the tenant label;
* ``flight_report`` renders the ``weight_page_in`` / ``weight_page_out``
  / ``tenant_switch`` records.

Run directly (``JAX_PLATFORMS=cpu python tools/multitenant_smoke.py``)
or from the CI multitenant_smoke step. Exits non-zero on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runtime thread-role assertions (analysis/roles.py) fail the smoke
    # loudly on a scheduler-thread violation (must precede seldon imports)
    os.environ.setdefault("SELDON_DEBUG_THREADS", "1")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.graph.engine_metrics import REGISTRY
    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.servers.generateserver import GenerateServer

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory(prefix="multitenant-smoke-") as root:
        cfg = {"vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 4,
               "n_kv_heads": 2, "d_ff": 64, "max_seq": 64}
        # distinct weights per tenant: jaxserver random-inits from the
        # config seed, so same architecture + different seed = a second
        # checkpoint that MUST produce different tokens
        dir_a = write_model_dir(os.path.join(root, "a"), "llm", cfg)
        dir_b = write_model_dir(
            os.path.join(root, "b"), "llm", {**cfg, "seed": 7}
        )
        common = dict(slots=2, steps_per_poll=2, warmup_prompt_lens=[4],
                      warmup_max_new_tokens=8)

        ded_a = GenerateServer(model_uri=dir_a, **common)
        ded_a.load()
        ded_b = GenerateServer(model_uri=dir_b, **common)
        ded_b.load()
        multi = GenerateServer(
            model_uri=dir_a,
            tenants=f"acme=strict,globex=best_effort@{dir_b}",
            weight_pager_host_bytes=64 << 20,
            tenant_min_resident_ms=0,
            **common,
        )
        multi.load()

        h_a = EngineHarness(ded_a, name="dedicated-acme").start()
        h_b = EngineHarness(ded_b, name="dedicated-globex").start()
        h_m = EngineHarness(multi, name="multitenant").start()

        def gen(port: int, prompt, tenant=None, temperature=0.0,
                seed=0, want_status=200):
            headers = {"Content-Type": "application/json"}
            if tenant is not None:
                headers["Seldon-Tenant"] = tenant
            conn = http.client.HTTPConnection("127.0.0.1", port)
            conn.request("POST", "/api/v0.1/predictions", json.dumps({
                "jsonData": {"prompt_tokens": [prompt], "max_new_tokens": 8,
                             "temperature": temperature, "seed": seed},
            }).encode(), headers)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            if resp.status != want_status:
                raise RuntimeError(f"HTTP {resp.status}: {payload[:160]!r}")
            if want_status != 200:
                return None
            return json.loads(payload)["jsonData"]["tokens"][0]

        try:
            prompts = [[5, 6, 7, 8], [9, 10, 11], [1, 2, 3, 4, 5, 6]]
            refs = {
                "acme": [gen(h_a.http_port, p) for p in prompts],
                "globex": [gen(h_b.http_port, p) for p in prompts],
            }
            # the two checkpoints really differ — otherwise identity
            # below would pass vacuously
            check("tenants serve distinct weights",
                  refs["acme"] != refs["globex"])

            # -- interleaved traffic: identity ACROSS paging --------------
            # alternate tenants per prompt so every request straddles a
            # demote→promote cycle of the other tenant
            for i, p in enumerate(prompts):
                for t in ("acme", "globex"):
                    got = gen(h_m.http_port, p, tenant=t)
                    check(f"greedy identical ({t}, prompt {i})",
                          got == refs[t][i],
                          "" if got == refs[t][i] else f"{got} != {refs[t][i]}")
            for i, p in enumerate(prompts):
                for t, port in (("acme", h_a.http_port),
                                ("globex", h_b.http_port)):
                    ref = gen(port, p, temperature=0.8, seed=17 + i)
                    got = gen(h_m.http_port, p, tenant=t,
                              temperature=0.8, seed=17 + i)
                    check(f"seeded identical ({t}, prompt {i})", got == ref,
                          "" if got == ref else f"{got} != {ref}")

            pstats = multi.tenant_pager.stats
            sstats = multi.tenant_scheduler.stats
            check("the interleave actually paged",
                  pstats["page_ins"] >= 3 and sstats["switches"] >= 2,
                  f"page_ins={pstats['page_ins']} switches={sstats['switches']}")

            # -- scale-to-zero: page back in without recompiling ----------
            b = multi.batcher
            sizes = {
                n: f._cache_size()
                for n, f in (("prefill", b._prefill_fn),
                             ("burst", b._burst_fn)) if f is not None
            }
            gen(h_m.http_port, prompts[0], tenant="globex")  # acme out
            gen(h_m.http_port, prompts[0], tenant="acme")    # ...and back
            recompiled = [
                n for n, f in (("prefill", b._prefill_fn),
                               ("burst", b._burst_fn))
                if f is not None and n in sizes and f._cache_size() != sizes[n]
            ]
            check("demote→promote cycle recompiled nothing",
                  not recompiled, f"recompiled={recompiled}")

            # -- unknown tenant refused typed -----------------------------
            try:
                gen(h_m.http_port, prompts[0], tenant="nobody")
                check("undeclared tenant refused", False, "served!")
            except RuntimeError as e:
                check("undeclared tenant refused", "200" not in str(e)[:12],
                      str(e)[:80])

            # -- exposition: tenant + pager series ------------------------
            expo = REGISTRY.expose()
            for series in ("seldon_engine_tenant_requests",
                           "seldon_engine_tenant_switches",
                           "seldon_engine_weight_page_ins",
                           "seldon_engine_weight_page_outs",
                           "seldon_engine_weight_pager_host_bytes",
                           "seldon_engine_weight_pager_resident_bytes",
                           "seldon_engine_tenants_registered",
                           "seldon_engine_tenant_ttft_seconds",
                           "seldon_engine_tenant_queue_wait_seconds"):
                check(f"exposition has {series}", series in expo)
            check("per-tenant series carry the tenant label",
                  'tenant="acme"' in expo and 'tenant="globex"' in expo)

            # -- flight report renders the paging story -------------------
            import importlib.util

            fr = os.path.join(os.path.dirname(__file__), "flight_report.py")
            spec = importlib.util.spec_from_file_location("flight_report", fr)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            text = mod.render(multi.flight_dump())
            check("flight report renders tenant switches",
                  "tenant switches:" in text)
            check("flight report renders the pager",
                  "weight pager:" in text and "weight pager staging" in text)
        finally:
            h_a.stop()
            h_b.stop()
            h_m.stop()
            ded_a.close()
            ded_b.close()
            multi.close()

    if failures:
        print(f"\nmultitenant smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nmultitenant smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
