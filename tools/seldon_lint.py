#!/usr/bin/env python
"""seldon-lint CLI: the repo's invariant gate.

Runs the ``seldon_core_tpu.analysis`` rule set (thread roles, lock
discipline, JAX hot-path hygiene, metric/annotation/clock contract
drift) over the given paths and fails on any finding not covered by the
checked-in baseline.

Usage:

    python tools/seldon_lint.py seldon_core_tpu tools
    python tools/seldon_lint.py --rules metric-drift,annotation-drift seldon_core_tpu tools
    python tools/seldon_lint.py --write-baseline seldon_core_tpu tools
    python tools/seldon_lint.py --list-rules

Exit codes: 0 = clean (or baseline-covered), 1 = new findings, 2 = usage.

Suppression: ``# seldon-lint: disable=<rule>`` on the flagged line or as
a standalone comment on the line above; always pair it with a
justification. The baseline (``tools/seldon_lint_baseline.json``)
covers accepted pre-existing findings so CI fails only on regressions;
refresh it with ``--write-baseline`` after an intentional change and
review the diff like code.
"""

from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

from seldon_core_tpu.analysis import core  # noqa: E402

DEFAULT_BASELINE = os.path.join(_HERE, "seldon_lint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="baseline file (default tools/seldon_lint_baseline.json)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="accept all current findings into the baseline file and exit 0",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--root", default=_ROOT,
        help="repo root for relative paths and docs/ discovery",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="findings only, no summary",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        from seldon_core_tpu import analysis

        print(analysis.__doc__.split("Rule catalog", 1)[1])
        return 0
    if not args.paths:
        ap.print_usage()
        return 2

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    baseline = (
        core.load_baseline(args.baseline)
        if not (args.no_baseline or args.write_baseline) else None
    )
    try:
        result = core.run_lint(
            args.paths, root=args.root, rules=rules, baseline=baseline
        )
    except ValueError as e:
        print(f"seldon-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        core.write_baseline(args.baseline, result.findings)
        print(
            f"seldon-lint: wrote {len(result.findings)} accepted finding(s) "
            f"to {os.path.relpath(args.baseline, args.root)}"
        )
        return 0

    for f in result.findings:
        print(f.format())
    if not args.quiet:
        print(
            f"seldon-lint: {len(result.findings)} finding(s) "
            f"({len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed) "
            f"across {result.files} file(s)",
            file=sys.stderr,
        )
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
