#!/usr/bin/env python
"""Regenerate ARCHITECTURE.md's numbers table from the newest BENCH_r*.json.

One source of truth: the driver-captured bench file. Run after every
round; the table between the GEN-NUMBERS markers is replaced wholesale.

    python tools/gen_arch_numbers.py
"""

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BEGIN = "<!-- GEN-NUMBERS:BEGIN (tools/gen_arch_numbers.py) -->"
END = "<!-- GEN-NUMBERS:END -->"


def latest_bench():
    files = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    if not files:
        sys.exit("no BENCH_r*.json found")
    return files[-1], json.load(open(files[-1]))


def fmt(n, nd=0):
    if n is None:
        return "—"
    return f"{n:,.{nd}f}"


def _extract_obj(text, key):
    """Brace-match the JSON object following 'key":' in possibly
    head-truncated text (the driver stores only the TAIL of stdout, so
    even the key itself may be cut — callers pass suffixes too)."""
    m = re.search(r'%s"\s*:\s*\{' % re.escape(key), text)
    if not m:
        return {}
    i = m.end() - 1
    depth = 0
    for j in range(i, len(text)):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                try:
                    return json.loads(text[i:j + 1])
                except ValueError:
                    return {}
    return {}


def rows_from(bench, bench_mtime=None):
    tail = bench.get("tail")
    if isinstance(tail, str):
        lines = [ln for ln in tail.strip().splitlines() if ln.strip()]
        line = lines[-1]
        try:
            payload = json.loads(line)
        except ValueError:
            payload = None
        if isinstance(payload, dict) and payload.get("compact"):
            # bench.py's final line is the compact harness summary; the
            # FULL single-line dump sits right above it — use it when the
            # capture kept it, else keep the compact skeleton (published
            # backfill below fills in the detail)
            for prev in reversed(lines[:-1]):
                try:
                    cand = json.loads(prev)
                except ValueError:
                    continue
                if isinstance(cand, dict) and "model_tier" in cand and not cand.get("compact"):
                    payload = cand
                    break
            if not isinstance(payload.get("model_tier"), dict):
                payload["model_tier"] = {}
            else:
                # the over-budget compact fallback stores bare numbers:
                # rows/s for the image/encoder tiers, tokens/s for the
                # generate tiers — rewrap under the key finish_rows reads
                def _rewrap(key, v):
                    if isinstance(v, dict):
                        return v
                    rate = ("rows_per_s"
                            if key.startswith(("resnet", "bert"))
                            else "tokens_per_s")
                    return {rate: v}

                payload["model_tier"] = {
                    k: _rewrap(k, v)
                    for k, v in payload["model_tier"].items()
                }
        if payload is None:
            # head-truncated capture: recover the named sub-objects and
            # scalars that survive in the tail
            payload = {"model_tier": _extract_obj(line, "model_tier"),
                       "binary_front": _extract_obj(line, "binary_front")
                       or _extract_obj(line, "ary_front"),
                       "grpc_front": _extract_obj(line, "grpc_front")
                       or _extract_obj(line, "rpc_front")}
            if not payload["model_tier"]:
                # even the model_tier key was cut: pick up whichever tier
                # sub-objects survive verbatim in the tail
                tiers = {}
                for key in ("resnet50_rest", "resnet50_device", "bert_grpc",
                            "bert_grpc_latency", "llm_generate", "llm_1b",
                            "llm_1b_latency", "llm_1b_spec",
                            "llm_generate_long", "llm_1b_long",
                            "llm_1b_shared_prefix"):
                    obj = _extract_obj(line, key)
                    if obj:
                        tiers[key] = obj
                payload["model_tier"] = tiers
            m = re.search(r'"unit": "req/s", "vs_baseline": ([0-9.]+)', line)
            if m:
                payload["vs_baseline"] = float(m.group(1))
            m = re.search(r'"value": ([0-9.]+), "unit": "req/s", "vs_baseline"', line)
            if m:
                payload["value"] = float(m.group(1))
    else:
        payload = bench
    mt = payload.get("model_tier", {})
    # Fallback (VERDICT r4 #4/#5): tail recovery can lose tiers the driver
    # truncated away. BASELINE.json["published"] is the SAME capture
    # (bench.py writes it in-run), so any tier missing from the tail is
    # taken from there; the front headlines likewise ride in
    # "published_fronts". The table can never drop tiers again.
    try:
        with open(os.path.join(ROOT, "BASELINE.json")) as f:
            baseline = json.load(f)
    except Exception:
        baseline = {}
    published = baseline.get("published") or {}
    fronts = baseline.get("published_fronts") or {}
    if (
        published.get("captured_at")
        and published.get("captured_at") == fronts.get("captured_at")
        # recency: a BENCH file materially newer than the stamped capture
        # means the driver ran after the last BASELINE write (e.g. bench
        # crashed pre-publish) — then the BENCH tail stays primary and
        # published only backfills, preserving "driver file is the source
        # of truth"
        and (
            bench_mtime is None
            or published["captured_at"] >= bench_mtime - 3600
        )
    ):
        # a stamped published capture is ONE coherent session (bench.py
        # writes tiers + fronts together); prefer it wholesale over
        # splicing tiers from different rounds — a driver-truncated tail
        # mixed with backfill would pair numbers from different tunnel
        # sessions in one table (VERDICT r4 #4/#5)
        import datetime as _dt

        mt = {k: v for k, v in published.items()
              if k not in ("device", "captured_at") and isinstance(v, dict)}
        payload = dict(payload)
        payload["model_tier"] = mt
        payload["binary_front"] = fronts.get("binary_front")
        payload["grpc_front"] = fronts.get("grpc_front")
        stub = fronts.get("stub_rest") or {}
        payload["value"] = stub.get("value")
        payload["vs_baseline"] = stub.get("vs_baseline")
        stamp = _dt.datetime.fromtimestamp(
            published["captured_at"], _dt.timezone.utc
        ).strftime("%Y-%m-%d %H:%M")
        payload["_backfill_note"] = (
            f"one coherent in-round capture from BASELINE.json published "
            f"({stamp} UTC, stamped by bench.py); the newest BENCH_r*.json "
            "is the driver's independent capture of the same tiers"
        )
        payload["_source"] = "published"
        return finish_rows(payload, mt)
    backfilled = []
    if isinstance(mt, dict):
        for key, tier in published.items():
            if key in ("device", "captured_at") or not isinstance(tier, dict):
                continue
            cur = mt.get(key)
            if not cur:
                mt[key] = tier
                backfilled.append(key)
            elif payload.get("compact") and isinstance(cur, dict):
                # compact skeleton tier: published fills in the detail,
                # the compact line's own numbers win where both exist
                mt[key] = {**tier, **cur}
    for key in ("binary_front", "grpc_front"):
        if not payload.get(key) and fronts.get(key):
            payload[key] = fronts[key]
            backfilled.append(key)
    if payload.get("value") is None and fronts.get("stub_rest"):
        payload["value"] = fronts["stub_rest"].get("value")
        payload.setdefault("vs_baseline", fronts["stub_rest"].get("vs_baseline"))
        backfilled.append("stub_rest")
    if backfilled:
        # provenance note rides with the table: same capture when bench.py
        # stamped published + published_fronts in the run that produced the
        # BENCH file, otherwise the note names the splice
        same = published.get("captured_at") == fronts.get("captured_at")
        payload["_backfill_note"] = (
            f"{len(backfilled)} entr{'y' if len(backfilled) == 1 else 'ies'} "
            f"({', '.join(sorted(backfilled))}) recovered from "
            "BASELINE.json published"
            + (" (same capture)" if same else
               " (NOTE: published/published_fronts carry different "
               "capture stamps)")
        )
    return finish_rows(payload, mt)


def finish_rows(payload, mt):
    rows = []
    if payload.get("value") is not None:
        rows.append((
            "Stub engine REST (1 core)",
            f"{fmt(payload.get('value'))} req/s",
            f"{payload.get('vs_baseline', '—')}x the reference's 16-core number",
        ))
    b = payload.get("binary_front") or {}
    if b:
        rows.append((
            "Binary protobuf front",
            f"{fmt(b.get('value'))} req/s",
            f"{b.get('vs_grpc_baseline', '—')}x the reference's gRPC headline",
        ))
    g = payload.get("grpc_front") or {}
    if g:
        rows.append((
            "Native gRPC front",
            f"{fmt(g.get('value'))} req/s",
            f"{g.get('vs_grpc_baseline', '—')}x the reference's gRPC headline "
            "(hand-rolled h2c + HPACK)",
        ))
    r = mt.get("resnet50_rest") or {}
    if r:
        extra = ""
        if r.get("pct_of_transport_roofline") is not None:
            extra = (f"; {r['pct_of_transport_roofline']}% of the measured "
                     f"H2D roofline ({r.get('h2d_mb_s', '—')} MB/s pipe)")
        rows.append((
            "ResNet-50, engine REST",
            f"{fmt(r.get('rows_per_s'))} rows/s, p50 {fmt(r.get('p50_ms'))} ms",
            f"{r.get('transport', 'wire tier')}{extra}",
        ))
    d = mt.get("resnet50_device") or {}
    if d:
        rows.append((
            "ResNet-50, device tier",
            f"{fmt(d.get('rows_per_s'))} rows/s, MFU {d.get('mfu_pct', '—')}%",
            "device-resident input; what the runtime sustains once tensors are in HBM",
        ))
    bg = mt.get("bert_grpc") or {}
    if bg:
        rows.append((
            "BERT-base, engine gRPC",
            f"{fmt(bg.get('rows_per_s'))} rows/s, MFU {bg.get('mfu_pct', '—')}%",
            "full stack at the chip's matmul roof",
        ))
    bl = mt.get("bert_grpc_latency") or {}
    if bl:
        rows.append((
            "BERT-base, latency tier",
            f"p50 {fmt(bl.get('p50_ms'), 1)} ms, p99 {fmt(bl.get('p99_ms'), 1)} ms",
            f"{bl.get('concurrency', '—')} closed-loop lanes, single-row "
            "requests — service latency, not queueing",
        ))
    g = mt.get("llm_generate") or {}
    if g:
        mbu = f", MBU {g['mbu_pct']}%" if g.get("mbu_pct") is not None else ""
        rows.append((
            "generate(), 0.2B decoder",
            f"{fmt(g.get('tokens_per_s'))} tok/s{mbu}",
            f"continuous batching, {g.get('slots', '—')} lanes",
        ))
    g1 = mt.get("llm_1b") or {}
    if g1:
        mbu = f", MBU {g1['mbu_pct']}%" if g1.get("mbu_pct") is not None else ""
        rows.append((
            f"generate(), {fmt(g1.get('n_params', 0) / 1e9, 2)}B decoder",
            f"{fmt(g1.get('tokens_per_s'))} tok/s{mbu}",
            f"bf16-resident flagship scale, {g1.get('slots', '—')} lanes",
        ))
    gL = mt.get("llm_1b_latency") or {}
    if gL:
        mbu = f", MBU {gL['mbu_pct']}%" if gL.get("mbu_pct") is not None else ""
        rows.append((
            "generate(), latency tier",
            f"{fmt(gL.get('tokens_per_s'))} tok/s, p50 {fmt(gL.get('p50_ms'))} ms{mbu}",
            f"{gL.get('slots', '—')} lanes, {fmt(gL.get('max_new_tokens'))}-token generations",
        ))
    gs = mt.get("llm_1b_spec") or {}
    if gs:
        sp = gs.get("speculation") or {}
        rows.append((
            "generate(), speculative decoding",
            f"{fmt(gs.get('tokens_per_s'))} tok/s "
            f"({gs.get('speedup_vs_spec_off', '—')}x vs off)",
            f"early-exit self-draft, {sp.get('tokens_per_round', '—')} tok/round accepted",
        ))
    gl = mt.get("llm_generate_long") or {}
    if gl:
        rows.append((
            f"generate(), {fmt(gl.get('prompt_len'))}-token prompts",
            f"{fmt(gl.get('tokens_per_s'))} tok/s",
            "flash prefill + live-prefix decode reads",
        ))
    gp = mt.get("llm_1b_shared_prefix") or {}
    if gp:
        ident = gp.get("greedy_identical")
        rows.append((
            "generate(), shared-prefix cache",
            f"{fmt(gp.get('tokens_per_s'))} tok/s "
            f"({gp.get('speedup_tokens_per_s', '—')}x vs cache-off)",
            "radix prefix KV cache, 32 prompts over 4 system prompts"
            + ("; greedy outputs identical" if ident else ""),
        ))
    g1l = mt.get("llm_1b_long") or {}
    if g1l:
        mbu = f", MBU {g1l['mbu_pct']}%" if g1l.get("mbu_pct") is not None else ""
        rows.append((
            f"generate(), 1.26B x {fmt(g1l.get('prompt_len'))}-token prompts",
            f"{fmt(g1l.get('tokens_per_s'))} tok/s{mbu}",
            "long context at flagship scale (grouped ~2k-key cache reads)",
        ))
    return rows, payload.get("_backfill_note"), payload.get("_source")


def main():
    path, bench = latest_bench()
    rows, note, src = rows_from(bench, bench_mtime=os.path.getmtime(path))
    source = (
        "`BASELINE.json` published"
        if src == "published"
        else f"`{os.path.basename(path)}`"
    )
    lines = [BEGIN,
             f"*(generated from {source} — do not edit by hand)*",
             "", "| Tier | Published | Reading |", "|---|---|---|"]
    for tier, published, reading in rows:
        lines.append(f"| {tier} | {published} | {reading} |")
    if note:
        lines.append("")
        lines.append(f"*{note}*")
    lines.append(END)
    block = "\n".join(lines)
    arch = os.path.join(ROOT, "ARCHITECTURE.md")
    text = open(arch).read()
    if BEGIN in text:
        text = re.sub(re.escape(BEGIN) + r".*?" + re.escape(END), block,
                      text, flags=re.S)
    else:
        sys.exit("ARCHITECTURE.md is missing the GEN-NUMBERS markers")
    open(arch, "w").write(text)
    print(f"regenerated numbers table from {os.path.basename(path)}")


if __name__ == "__main__":
    main()
