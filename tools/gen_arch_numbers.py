#!/usr/bin/env python
"""Regenerate ARCHITECTURE.md's numbers table from BASELINE.json ONLY.

One source of truth (VERDICT r5 #7): earlier rounds spliced the table
from the newest BENCH_r*.json tail with BASELINE.json backfill, and a
mid-session capture once published headline numbers that disagreed with
the end-of-round BASELINE — two artifacts in one repo stating different
numbers for the same tier. Now the table reads exactly one capture —
``BASELINE.json`` ``published`` / ``published_fronts`` (stamped
atomically by bench.py at capture time) — and the provenance line names
the source file, the capture keys, and the capture timestamp, so any
future divergence is attributable on sight.

    python tools/gen_arch_numbers.py
"""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BEGIN = "<!-- GEN-NUMBERS:BEGIN (tools/gen_arch_numbers.py) -->"
END = "<!-- GEN-NUMBERS:END -->"


def fmt(n, nd=0):
    if n is None:
        return "—"
    return f"{n:,.{nd}f}"


def load_capture():
    """The one coherent capture: BASELINE.json published (+ fronts)."""
    path = os.path.join(ROOT, "BASELINE.json")
    with open(path) as f:
        baseline = json.load(f)
    published = baseline.get("published") or {}
    fronts = baseline.get("published_fronts") or {}
    if not published:
        sys.exit("BASELINE.json has no 'published' capture — run bench.py")
    mt = {
        k: v for k, v in published.items()
        if k not in ("device", "captured_at") and isinstance(v, dict)
    }
    stamps = {published.get("captured_at"), fronts.get("captured_at") or
              published.get("captured_at")}
    return mt, fronts, published.get("captured_at"), len(stamps) == 1


def provenance(captured_at, coherent):
    import datetime as _dt

    stamp = "unknown time"
    if captured_at:
        stamp = _dt.datetime.fromtimestamp(
            captured_at, _dt.timezone.utc
        ).strftime("%Y-%m-%d %H:%M UTC")
    line = (
        f"*(generated from `BASELINE.json` keys `published` + "
        f"`published_fronts`, captured {stamp} by bench.py — the single "
        "source of truth for this table; do not edit by hand)*"
    )
    if not coherent:
        line += (
            "\n\n*WARNING: `published` and `published_fronts` carry "
            "different capture stamps — rerun bench.py for one coherent "
            "capture.*"
        )
    return line


def rows_from(mt, fronts):
    rows = []
    stub = fronts.get("stub_rest") or {}
    if stub.get("value") is not None:
        rows.append((
            "Stub engine REST (1 core)",
            f"{fmt(stub.get('value'))} req/s",
            f"{stub.get('vs_baseline', '—')}x the reference's 16-core number",
        ))
    b = fronts.get("binary_front") or {}
    if b:
        rows.append((
            "Binary protobuf front",
            f"{fmt(b.get('value'))} req/s",
            f"{b.get('vs_grpc_baseline', '—')}x the reference's gRPC headline",
        ))
    g = fronts.get("grpc_front") or {}
    if g:
        rows.append((
            "Native gRPC front",
            f"{fmt(g.get('value'))} req/s",
            f"{g.get('vs_grpc_baseline', '—')}x the reference's gRPC headline "
            "(hand-rolled h2c + HPACK)",
        ))
    r = mt.get("resnet50_rest") or {}
    if r:
        extra = ""
        if r.get("pct_of_transport_roofline") is not None:
            extra = (f"; {r['pct_of_transport_roofline']}% of the measured "
                     f"H2D roofline ({r.get('h2d_mb_s', '—')} MB/s pipe)")
        rows.append((
            "ResNet-50, engine REST",
            f"{fmt(r.get('rows_per_s'))} rows/s, p50 {fmt(r.get('p50_ms'))} ms",
            f"{r.get('transport', 'wire tier')}{extra}",
        ))
    d = mt.get("resnet50_device") or {}
    if d:
        rows.append((
            "ResNet-50, device tier",
            f"{fmt(d.get('rows_per_s'))} rows/s, MFU {d.get('mfu_pct', '—')}%",
            "device-resident input; what the runtime sustains once tensors are in HBM",
        ))
    bg = mt.get("bert_grpc") or {}
    if bg:
        rows.append((
            "BERT-base, engine gRPC",
            f"{fmt(bg.get('rows_per_s'))} rows/s, MFU {bg.get('mfu_pct', '—')}%",
            "full stack at the chip's matmul roof",
        ))
    bl = mt.get("bert_grpc_latency") or {}
    if bl:
        svc = bl.get("device_service_ms")
        svc_note = (
            f"; device service {svc} ms/row" if svc
            else "; device service withheld (non-positive slope)"
            if "device_service_ms" in bl else ""
        )
        rows.append((
            "BERT-base, latency tier",
            f"p50 {fmt(bl.get('p50_ms'), 1)} ms, p99 {fmt(bl.get('p99_ms'), 1)} ms",
            f"{bl.get('concurrency', '—')} closed-loop lanes, single-row "
            f"requests — service latency, not queueing{svc_note}",
        ))
    g = mt.get("llm_generate") or {}
    if g:
        mbu = f", MBU {g['mbu_pct']}%" if g.get("mbu_pct") is not None else ""
        floor = (
            f"; {g['pct_of_dispatch_floor']}% of the dispatch floor"
            if g.get("pct_of_dispatch_floor") is not None else ""
        )
        fd = g.get("fused_decode") or {}
        if fd.get("pct_of_dispatch_floor_on") is not None:
            # fused multi-step decode: both modes against the SAME
            # step-at-a-time dispatch bound, so the on-vs-off delta IS
            # the floor being killed
            floor = (
                f"; dispatch floor {fd['pct_of_dispatch_floor_on']}% fused"
                f"-on vs {fd['pct_of_dispatch_floor_off']}% off"
                f" (K={fd.get('fused_steps_per_dispatch', '—')}"
                + (", bytes identical"
                   if fd.get("greedy_identical") and fd.get("sampled_identical")
                   else "")
                + ")"
            )
        rows.append((
            "generate(), 0.2B decoder",
            f"{fmt(g.get('tokens_per_s'))} tok/s{mbu}",
            f"continuous batching, {g.get('slots', '—')} lanes{floor}",
        ))
    g1 = mt.get("llm_1b") or {}
    if g1:
        mbu = f", MBU {g1['mbu_pct']}%" if g1.get("mbu_pct") is not None else ""
        rows.append((
            f"generate(), {fmt(g1.get('n_params', 0) / 1e9, 2)}B decoder",
            f"{fmt(g1.get('tokens_per_s'))} tok/s{mbu}",
            f"bf16-resident flagship scale, {g1.get('slots', '—')} lanes",
        ))
    gL = mt.get("llm_1b_latency") or {}
    if gL:
        mbu = f", MBU {gL['mbu_pct']}%" if gL.get("mbu_pct") is not None else ""
        rows.append((
            "generate(), latency tier",
            f"{fmt(gL.get('tokens_per_s'))} tok/s, p50 {fmt(gL.get('p50_ms'))} ms{mbu}",
            f"{gL.get('slots', '—')} lanes, {fmt(gL.get('max_new_tokens'))}-token generations",
        ))
    gs = mt.get("llm_1b_spec") or {}
    if gs:
        sp = gs.get("speculation") or {}
        rows.append((
            "generate(), speculative decoding",
            f"{fmt(gs.get('tokens_per_s'))} tok/s "
            f"({gs.get('speedup_vs_spec_off', '—')}x vs off)",
            f"early-exit self-draft, {sp.get('tokens_per_round', '—')} tok/round accepted",
        ))
    gl = mt.get("llm_generate_long") or {}
    if gl:
        mbu = f", MBU {gl['mbu_pct']}%" if gl.get("mbu_pct") is not None else ""
        rows.append((
            f"generate(), {fmt(gl.get('prompt_len'))}-token prompts",
            f"{fmt(gl.get('tokens_per_s'))} tok/s{mbu}",
            "flash prefill + live-prefix decode reads",
        ))
    gp = mt.get("llm_1b_shared_prefix") or {}
    if gp:
        ident = gp.get("greedy_identical")
        rows.append((
            "generate(), shared-prefix cache",
            f"{fmt(gp.get('tokens_per_s'))} tok/s "
            f"({gp.get('speedup_tokens_per_s', '—')}x vs cache-off)",
            "radix prefix KV cache, 32 prompts over 4 system prompts"
            + ("; greedy outputs identical" if ident else ""),
        ))
    gr = mt.get("llm_1b_rollout") or {}
    if gr:
        rb = gr.get("rollback") or {}
        rolled = rb.get("restored_to_baseline")
        rows.append((
            "generate(), canary rollout",
            f"{fmt(gr.get('tokens_per_s'))} tok/s, "
            f"{gr.get('mirror_overhead_pct', '—')}% mirror overhead",
            f"SLO-gated ramp {gr.get('steps', '—')}"
            + ("; greedy identical every step"
               if gr.get("greedy_identical") else "")
            + ("; auto-rollback in 1 interval" if rolled else ""),
        ))
    gd = mt.get("llm_1b_disagg") or {}
    if gd:
        iso = gd.get("isolation") or {}
        dd = gd.get("transfer_dedup") or {}
        ident = gd.get("greedy_identical")
        rows.append((
            "generate(), disaggregated prefill/decode",
            f"short-request TTFT p99 ratio {iso.get('disagg_ttft_p99_ratio', '—')}x "
            f"(unified {iso.get('unified_ttft_p99_ratio', '—')}x) under "
            f"{fmt(gd.get('long_prompt_len'))}-token injection",
            "KV-slab handoff, loopback+TCP"
            + ("; greedy bytes identical" if ident else "")
            + (f"; {fmt(dd.get('kv_transfer_bytes_saved', 0))} B "
               "transfer-deduped" if dd.get("kv_transfer_bytes_saved") else ""),
        ))
    gc = mt.get("llm_1b_chaos") or {}
    if gc:
        rc = gc.get("recovery_counters") or {}
        rows.append((
            "generate(), chaos (fault-tolerant disagg)",
            f"error rate {gc.get('error_rate', '—')} over "
            f"{fmt(gc.get('requests_total'))} chaotic requests",
            "KV faults x5 + pool outage + scheduler death"
            + ("; completed outputs byte-identical"
               if gc.get("greedy_identical") else "")
            + ("; no hangs" if gc.get("no_hang") else "")
            + (f"; {rc.get('batcher_restarts', 0)} restart(s), "
               f"{rc.get('peer_ejections', 0)} ejection(s)"
               if rc.get("all_exercised") else ""),
        ))
    gp = mt.get("llm_1b_pressure") or {}
    if gp:
        rows.append((
            "generate(), HBM pressure (preempt + resume)",
            f"{fmt(gp.get('preemptions'))} preemption(s), TTFT "
            f"{gp.get('ttft_inflation_x', '—')}x baseline under a "
            f"{fmt(gp.get('shrink_to_bytes'))}-byte ledger",
            "mid-run ledger shrink; recompute-requeue"
            + ("; greedy + seeded-sampling bytes identical"
               if gp.get("greedy_identical") and gp.get("sampled_identical")
               else "")
            + ("; no hangs" if gp.get("no_hang") else ""),
        ))
    rg = mt.get("llm_rag") or {}
    if rg:
        rows.append((
            "RAG graph, fusion (embed->retrieve->rerank->generate)",
            f"p50 {fmt(rg.get('p50_fused_ms'), 2)} ms fused vs "
            f"{fmt(rg.get('p50_hop_ms'), 2)} ms hop-by-hop "
            f"({rg.get('fused_speedup', '—')}x)",
            f"{len(rg.get('segment_stages') or [])} stages -> 1 dispatch"
            + ("; greedy bytes identical incl. generate tail"
               if rg.get("greedy_identical") else "")
            + ("; chaos fallback counted"
               if rg.get("fallback_exercised") else ""),
        ))
    gk = mt.get("llm_1b_kvtier") or {}
    if gk:
        on = gk.get("tier_on") or {}
        rows.append((
            "generate(), tiered KV memory (host spill tier)",
            f"{fmt(on.get('kv_tier_hits'))} copy-back resume(s) vs "
            f"{fmt(gk.get('destroy_replayed_tokens'))} tokens replayed "
            "tier-off",
            "same ledger shrink, tier off vs on"
            + ("; greedy bytes identical both modes"
               if gk.get("greedy_identical") else "")
            + ("; replay fallbacks quiet"
               if gk.get("copyback_exercised") else ""),
        ))
    gm = mt.get("llm_1b_migration") or {}
    if gm:
        rows.append((
            "generate(), live migration (drain + resume tokens)",
            f"{fmt(gm.get('drained'))} request(s) drained mid-decode, "
            f"{fmt(gm.get('checkpoints_migrated'))} checkpoint(s) "
            "migrated",
            "graceful drain + member-kill resume token"
            + ("; bytes identical, zero client failures"
               if gm.get("greedy_identical") and gm.get("zero_failures")
               else "")
            + ("; no stream span re-sent"
               if gm.get("stream_no_resend") else "")
            + (f"; kill resumed with {gm.get('kill_retries', 0)} retry"
               if gm.get("kill_resume_identical") else ""),
        ))
    gsh = mt.get("llm_1b_sharded") or {}
    if gsh and not gsh.get("skipped"):
        mbu = (
            f", per-chip MBU {gsh['mbu_pct']}% vs {gsh.get('plain_mbu_pct', '—')}%"
            if gsh.get("mbu_pct") is not None else ""
        )
        rows.append((
            "generate(), pod-scale sharded serving",
            f"{fmt(gsh.get('tokens_per_s'))} tok/s sharded vs "
            f"{fmt(gsh.get('plain_tokens_per_s'))} 1-device, p50 "
            f"{fmt(gsh.get('p50_ms'))} vs {fmt(gsh.get('plain_p50_ms'))} ms"
            # on a host-emulated mesh the raw p50 carries the N-way
            # timesharing of one socket; the per-chip verdict is the
            # meaningful regression gate there (see bench_sharded)
            + (" (per-chip no-slower)"
               if gsh.get("p50_no_slower_per_chip")
               and not gsh.get("p50_no_slower") else "")
            + f"{mbu}",
            f"mesh {gsh.get('mesh_shape', '—')}, params+KV at "
            f"1/{gsh.get('kv_shard', '—')} per chip"
            + ("; greedy + seeded bytes identical"
               if gsh.get("greedy_identical") and gsh.get("sampled_identical")
               else ""),
        ))
    gmt = mt.get("llm_1b_multitenant") or {}
    if gmt:
        ttfts = gmt.get("ttft_p99_ms_by_tenant") or {}
        ttft_bit = ", ".join(
            f"{t} {fmt(v, 2)}" for t, v in ttfts.items()
        )
        rows.append((
            "generate(), multi-tenant weight paging "
            f"({len(gmt.get('tenants') or {})} checkpoints, 1 server)",
            f"{fmt(gmt.get('tokens_per_s'))} tok/s paged vs "
            f"{fmt(gmt.get('dedicated_tokens_per_s'))} dedicated "
            f"({gmt.get('throughput_ratio', '—')}x), "
            f"{fmt(gmt.get('page_ins'))} page-in(s)"
            + (f"; TTFT p99 ms by tenant: {ttft_bit}" if ttft_bit else ""),
            f"Zipf {tuple(gmt.get('zipf') or ())} mix, "
            "strict/standard/best_effort SLO classes"
            + ("; greedy + seeded bytes identical across paging"
               if gmt.get("greedy_identical") and gmt.get("sampled_identical")
               else ""),
        ))
    gst = mt.get("llm_1b_storm") or {}
    if gst:
        pw = gst.get("planner") or {}
        rows.append((
            "generate(), autonomic planner storm",
            f"{fmt(pw.get('tokens_per_s'))} tok/s planner-driven vs "
            f"{fmt((gst.get('static') or {}).get('tokens_per_s'))} "
            f"hand-tuned, {fmt(gst.get('retunes_applied'))} retune(s)",
            "seeded diurnal+burst storm, mistuned boot"
            + ("; converged to the hand-tuned config"
               if gst.get("planner_converged") else "")
            + ("; greedy bytes identical across the retune"
               if gst.get("greedy_identical") else "")
            + ("; post-retune TTFT p99 under objective"
               if gst.get("slo_held") else ""),
        ))
    g1l = mt.get("llm_1b_long") or {}
    if g1l:
        mbu = f", MBU {g1l['mbu_pct']}%" if g1l.get("mbu_pct") is not None else ""
        rows.append((
            f"generate(), 1.26B x {fmt(g1l.get('prompt_len'))}-token prompts",
            f"{fmt(g1l.get('tokens_per_s'))} tok/s{mbu}",
            "long context at flagship scale (depth-aware bursts; "
            "ablation grid in BENCH)",
        ))
    return rows


def main():
    mt, fronts, captured_at, coherent = load_capture()
    rows = rows_from(mt, fronts)
    lines = [BEGIN, provenance(captured_at, coherent),
             "", "| Tier | Published | Reading |", "|---|---|---|"]
    for tier, published, reading in rows:
        lines.append(f"| {tier} | {published} | {reading} |")
    lines.append(END)
    block = "\n".join(lines)
    arch = os.path.join(ROOT, "ARCHITECTURE.md")
    text = open(arch).read()
    if BEGIN in text:
        text = re.sub(re.escape(BEGIN) + r".*?" + re.escape(END), block,
                      text, flags=re.S)
    else:
        sys.exit("ARCHITECTURE.md is missing the GEN-NUMBERS markers")
    open(arch, "w").write(text)
    print("regenerated numbers table from BASELINE.json published")


if __name__ == "__main__":
    main()
