#!/usr/bin/env python3
"""CI smoke for the generation-path observability stack.

Boots a tiny generate server behind a real engine on sockets, runs a few
requests, then asserts the whole observability surface is live:

* ``/prometheus`` exposes the first-class SLO series
  (``seldon_engine_generate_ttft_seconds`` / ``..._tpot_seconds`` /
  ``..._queue_wait_seconds`` histograms);
* ``/flightrecorder`` returns well-formed JSON with per-poll records and
  an SLO summary (and ``tools/flight_report.py`` can render it);
* ``/traces`` shows a generate request as ONE stitched trace:
  queue-wait → prefill → decode spans under the engine's root span.

Run directly (``JAX_PLATFORMS=cpu python tools/observability_smoke.py``)
or from the CI observability step. Exits non-zero on any failed check.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.servers.generateserver import GenerateServer
    from seldon_core_tpu.tracing import get_tracer, init_tracer

    init_tracer("obs-smoke", enabled=True)
    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory(prefix="obs-smoke-") as root:
        model_dir = write_model_dir(root, "llm", {
            "vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 2,
            "n_kv_heads": 2, "d_ff": 64, "max_seq": 64,
        })
        component = GenerateServer(model_uri=model_dir, slots=2,
                                   steps_per_poll=4, attn_bucket=16)
        component.load()
        harness = EngineHarness(component, name="obs-smoke").start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", harness.http_port)
            body = json.dumps({"jsonData": {
                "prompt_tokens": [[1, 2, 3, 4, 5]],
                "max_new_tokens": 6, "temperature": 0.0,
            }}).encode()
            for _ in range(3):
                conn.request("POST", "/api/v0.1/predictions", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
                check("predict 200", resp.status == 200, payload[:120].decode("utf-8", "replace"))

            conn.request("GET", "/metrics")
            metrics = conn.getresponse().read().decode()
            for series in (
                "seldon_engine_generate_ttft_seconds",
                "seldon_engine_generate_tpot_seconds",
                "seldon_engine_generate_queue_wait_seconds",
            ):
                check(f"/metrics has {series}", f"{series}_bucket" in metrics)

            conn.request("GET", "/flightrecorder")
            resp = conn.getresponse()
            check("/flightrecorder 200", resp.status == 200)
            fr = json.loads(resp.read())
            units = fr.get("units") or {}
            check("/flightrecorder has a unit dump", bool(units))
            dump = next(iter(units.values()), {})
            check("flight recorder recorded polls",
                  any(e.get("type") == "poll" for e in dump.get("entries", [])))
            check("flight recorder has SLO summary",
                  bool((dump.get("slo") or {}).get("samples")))

            sys.path.insert(0, os.path.dirname(__file__))
            from flight_report import render

            report = render(fr)
            check("flight_report renders", "flight report" in report
                  and "SLO over" in report)

            conn.request("GET", "/traces?operation=gen.")
            resp = conn.getresponse()
            check("/traces 200", resp.status == 200)
            traces = json.loads(resp.read())
            ops = {
                s["operationName"]
                for t in traces.get("data", [])
                for s in t.get("spans", [])
            }
            for op in ("gen.queue_wait", "gen.prefill", "gen.decode"):
                check(f"/traces has {op}", op in ops, str(sorted(ops)))
            # one request = one stitched trace: a gen.decode span shares its
            # trace id with the engine's root predictions span
            full = get_tracer().export_jaeger()
            stitched = False
            for t in full["data"]:
                names = {s["operationName"] for s in t["spans"]}
                if "predictions" in names and "gen.decode" in names:
                    stitched = True
            check("generate spans stitch under the engine root", stitched)
        finally:
            harness.stop()
            if component.batcher is not None:
                component.batcher.close()
            init_tracer(enabled=False)

    if failures:
        print(f"\nobservability smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nobservability smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
