#!/usr/bin/env python3
"""CI smoke for the generation-path observability stack.

Boots a tiny generate server behind a real engine on sockets, runs a few
requests, then asserts the whole observability surface is live:

* ``/prometheus`` exposes the first-class SLO series
  (``seldon_engine_generate_ttft_seconds`` / ``..._tpot_seconds`` /
  ``..._queue_wait_seconds`` histograms) plus — with the device-time
  profiler and SLO burn engine on — the
  ``seldon_engine_device_time_seconds`` attribution counters and the
  ``seldon_engine_slo_burn_rate`` gauges;
* ``/flightrecorder`` returns well-formed JSON with per-poll records and
  an SLO summary (and ``tools/flight_report.py`` can render it,
  device-time ledger breakdown included);
* ``/traces`` shows a generate request as ONE stitched trace:
  queue-wait → prefill → decode spans under the engine's root span;
* a TWO-member deployment reconciled through the controller serves
  ``/fleet`` per member, the controller's scrape loop merges both into
  one deployment-scope metric plane, and an absurdly tight SLO
  objective forces a ``page`` burn verdict the autoscaler feed sees.

Run directly (``JAX_PLATFORMS=cpu python tools/observability_smoke.py``)
or from the CI observability step. Exits non-zero on any failed check.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.servers.generateserver import GenerateServer
    from seldon_core_tpu.tracing import get_tracer, init_tracer

    init_tracer("obs-smoke", enabled=True)
    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory(prefix="obs-smoke-") as root:
        model_dir = write_model_dir(root, "llm", {
            "vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 2,
            "n_kv_heads": 2, "d_ff": 64, "max_seq": 64,
        })
        component = GenerateServer(model_uri=model_dir, slots=2,
                                   steps_per_poll=4, attn_bucket=16,
                                   profiler=1, profiler_deep_every=3,
                                   profiler_hbm_gb_s=100.0,
                                   slo_objectives="ttft:0.001:0.99")
        component.load()
        harness = EngineHarness(component, name="obs-smoke").start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", harness.http_port)
            body = json.dumps({"jsonData": {
                "prompt_tokens": [[1, 2, 3, 4, 5]],
                "max_new_tokens": 6, "temperature": 0.0,
            }}).encode()
            for _ in range(3):
                conn.request("POST", "/api/v0.1/predictions", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
                check("predict 200", resp.status == 200, payload[:120].decode("utf-8", "replace"))

            conn.request("GET", "/metrics")
            metrics = conn.getresponse().read().decode()
            for series in (
                "seldon_engine_generate_ttft_seconds",
                "seldon_engine_generate_tpot_seconds",
                "seldon_engine_generate_queue_wait_seconds",
            ):
                check(f"/metrics has {series}", f"{series}_bucket" in metrics)
            # device-time ledger exposition: attribution counters with a
            # kind label, the live-MBU gauge, and the burn-rate series a
            # 1µs TTFT objective forces into a paging verdict
            check("/metrics has seldon_engine_device_time_seconds{kind=}",
                  "seldon_engine_device_time_seconds" in metrics
                  and 'kind="prefill"' in metrics)
            for series in ("seldon_engine_device_dispatches",
                           "seldon_engine_mbu_pct",
                           "seldon_engine_slo_burn_rate",
                           "seldon_engine_slo_burn_verdicts"):
                check(f"/metrics has {series}", series in metrics)
            check("forced burn verdict pages",
                  'severity="page"' in metrics)

            conn.request("GET", "/flightrecorder")
            resp = conn.getresponse()
            check("/flightrecorder 200", resp.status == 200)
            fr = json.loads(resp.read())
            units = fr.get("units") or {}
            check("/flightrecorder has a unit dump", bool(units))
            dump = next(iter(units.values()), {})
            check("flight recorder recorded polls",
                  any(e.get("type") == "poll" for e in dump.get("entries", [])))
            check("flight recorder has SLO summary",
                  bool((dump.get("slo") or {}).get("samples")))

            sys.path.insert(0, os.path.dirname(__file__))
            from flight_report import render

            report = render(fr)
            check("flight_report renders", "flight report" in report
                  and "SLO over" in report)
            check("flight_report renders the device-time ledger",
                  "device-time ledger" in report)
            check("flight_report renders the burn verdict",
                  "SLO burn PAGE" in report)

            conn.request("GET", "/traces?operation=gen.")
            resp = conn.getresponse()
            check("/traces 200", resp.status == 200)
            traces = json.loads(resp.read())
            ops = {
                s["operationName"]
                for t in traces.get("data", [])
                for s in t.get("spans", [])
            }
            for op in ("gen.queue_wait", "gen.prefill", "gen.decode"):
                check(f"/traces has {op}", op in ops, str(sorted(ops)))
            # one request = one stitched trace: a gen.decode span shares its
            # trace id with the engine's root predictions span
            full = get_tracer().export_jaeger()
            stitched = False
            for t in full["data"]:
                names = {s["operationName"] for s in t["spans"]}
                if "predictions" in names and "gen.decode" in names:
                    stitched = True
            check("generate spans stitch under the engine root", stitched)
        finally:
            harness.stop()
            if component.batcher is not None:
                component.batcher.close()
            init_tracer(enabled=False)

    fleet_smoke(check)

    if failures:
        print(f"\nobservability smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nobservability smoke passed")
    return 0


def fleet_smoke(check) -> None:
    """Two-member deployment through the controller: every member serves
    ``/fleet``, the scrape loop merges both into the deployment-scope
    registry with member labels, and the 1µs TTFT objective forces a
    paging burn verdict into the autoscaler feed."""
    import asyncio
    import json
    import tempfile

    from seldon_core_tpu.controlplane.ingress import Gateway
    from seldon_core_tpu.controlplane.reconciler import DeploymentController
    from seldon_core_tpu.controlplane.resource import SeldonDeployment
    from seldon_core_tpu.controlplane.store import ResourceStore

    with tempfile.TemporaryDirectory(prefix="obs-smoke-fleet-") as root:
        import os

        model_dir = os.path.join(root, "llm")
        os.makedirs(model_dir)
        with open(os.path.join(model_dir, "jax_config.json"), "w") as f:
            json.dump({"family": "llm", "config": {
                "vocab_size": 256, "d_model": 32, "n_layers": 2,
                "n_heads": 2, "n_kv_heads": 2, "d_ff": 64, "max_seq": 64,
                "seed": 0,
            }}, f)
        dep = SeldonDeployment.from_dict({
            "metadata": {"name": "gen", "namespace": "default"},
            "spec": {"predictors": [{
                "name": "main", "traffic": 100, "replicas": 2,
                "graph": {
                    "name": "llm",
                    "implementation": "GENERATE_SERVER",
                    "modelUri": model_dir,
                    "parameters": [
                        {"name": "slots", "value": "2", "type": "INT"},
                        {"name": "max_seq", "value": "64", "type": "INT"},
                        {"name": "profiler", "value": "1", "type": "INT"},
                        {"name": "slo_objectives",
                         "value": "ttft:0.001:0.99", "type": "STRING"},
                    ],
                },
            }]},
        })

        async def run():
            store = ResourceStore()
            gw = Gateway(seed=0)
            ctl = DeploymentController(store, gateway=gw)
            try:
                store.apply(dep)
                status = await ctl.reconcile(dep)
                check("fleet: 2-member deployment reconciles",
                      status.state == "Available", status.description)
                check("fleet: two members placed",
                      len(ctl.components) == 2, str(list(ctl.components)))
                primary, _ = gw.select("default/gen")
                for i in range(3):
                    out = await gw._forward(
                        primary, "/api/v0.1/predictions",
                        {"jsonData": {"prompt_tokens": [[3, 17, 42]],
                                      "max_new_tokens": 5}},
                    )
                    check(f"fleet: predict {i} answered",
                          bool(out.get("jsonData", {}).get("tokens")))
                # every member answers /fleet (the scrape's input) with
                # mergeable primitives + unit summaries
                for name, (handle, _) in ctl.components.items():
                    snap = await handle.fleet()
                    check(f"fleet: member {name} serves /fleet",
                          snap is not None and "metrics" in snap
                          and "units" in snap)
                units = await ctl.fleet_scrape_once()
                check("fleet: scrape covered both members",
                      len(units) == 2, str(list(units)))
                text = ctl.fleet_metrics.expose()
                check("fleet: merged plane has device-time attribution",
                      "seldon_engine_device_time_seconds" in text)
                check("fleet: merged series carry member labels",
                      'member="' in text and 'deployment="' in text)
                series = "seldon_engine_generate_ttft_seconds"
                check("fleet: merged TTFT histogram buckets",
                      f"{series}_bucket" in text)
                verdicts = [
                    v for vs in ctl._burn_verdicts.values() for v in vs
                ]
                check("fleet: forced burn verdict pages",
                      any(v.get("severity") == "page" for v in verdicts),
                      str(verdicts[:2]))
                check("fleet: page verdict feeds the autoscaler signal",
                      any(
                          ctl._worst_burn(dep_key, pred) == "page"
                          for (dep_key, pred) in ctl._burn_verdicts
                      ))
            finally:
                await ctl.shutdown()

        asyncio.run(run())


if __name__ == "__main__":
    raise SystemExit(main())
