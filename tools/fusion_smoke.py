#!/usr/bin/env python3
"""CI smoke for graph fusion + the RAG workload.

Boots TWO engines over the SAME loaded components on real sockets — one
with ``seldon.io/fuse: "true"``, one without — serving the RAG graph
(embed -> retrieve -> rerank -> RAG_PROMPT_BUILDER -> generate), then
asserts the whole fusion surface is live:

* fused and unfused responses are byte-identical (token output, tags,
  requestPath; wall-clock TIMER telemetry excluded) — the greedy
  generate tail included;
* the fused engine's ``/metrics`` exposes
  ``seldon_engine_fused_segments`` with dispatches counted (and no
  ``seldon_engine_fusion_fallbacks`` on the clean path);
* ``/flightrecorder`` carries the ``(fusion)`` pseudo-unit dump with
  ``fused_dispatch`` records, and ``tools/flight_report.py`` renders it
  (with the fallback-rate DIAGNOSIS when fallbacks dominate);
* a faulted engine (fault injector on the interior rerank unit) serves
  identical output per-unit with the fallback COUNTED in
  ``seldon_engine_fusion_fallbacks{reason="faults"}``.

Run directly (``JAX_PLATFORMS=cpu python tools/fusion_smoke.py``) or
from the CI fusion step. Exits non-zero on any failed check.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _scrub(payload: dict) -> dict:
    payload = json.loads(json.dumps(payload))
    meta = payload.get("meta") or {}
    meta.pop("puid", None)
    if "metrics" in meta:
        meta["metrics"] = [
            m for m in meta["metrics"] if m.get("type") != "TIMER"
        ]
    return payload


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    import numpy as np

    from seldon_core_tpu.graph.units import RagPromptBuilder
    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.resilience.faults import FaultInjector
    from seldon_core_tpu.servers.generateserver import GenerateServer
    from seldon_core_tpu.servers.jaxserver import JAXServer

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}"
              + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    E, K, L, V = 16, 4, 6, 256
    with tempfile.TemporaryDirectory(prefix="fusion-smoke-") as root:
        bert_dir = write_model_dir(root, "bert", {
            "vocab_size": V, "d_model": 32, "n_layers": 2, "n_heads": 2,
            "d_ff": 64, "max_seq": 32, "num_classes": E,
        })
        ret_cfg = {"corpus_size": 64, "d_embed": E, "top_k": K,
                   "doc_len": L, "vocab_size": V, "seed": 7}
        ret_dir = write_model_dir(root, "retrieval", ret_cfg)
        rer_dir = write_model_dir(root, "reranker", ret_cfg)
        llm_dir = write_model_dir(root, "llm", {
            "vocab_size": V, "d_model": 32, "n_layers": 2, "n_heads": 2,
            "n_kv_heads": 2, "d_ff": 64, "max_seq": 32,
        })
        embed = JAXServer(model_uri=bert_dir)
        embed.load()
        retrieve = JAXServer(model_uri=ret_dir)
        retrieve.load()
        rerank = JAXServer(model_uri=rer_dir)
        rerank.load()
        gen = GenerateServer(model_uri=llm_dir, slots=2, steps_per_poll=1,
                             warmup_prompt_lens=[L],
                             warmup_max_new_tokens=8)
        gen.load()
        registry = {
            "embed": embed, "retrieve": retrieve, "rerank": rerank,
            "prompt": RagPromptBuilder(max_new_tokens=8), "generate": gen,
        }
        graph = {
            "name": "embed", "type": "MODEL", "children": [{
                "name": "retrieve", "type": "MODEL", "children": [{
                    "name": "rerank", "type": "MODEL", "children": [{
                        "name": "prompt",
                        "implementation": "RAG_PROMPT_BUILDER",
                        "children": [
                            {"name": "generate", "type": "MODEL"}
                        ],
                    }],
                }],
            }],
        }

        from seldon_core_tpu.graph.engine_metrics import MetricsRegistry

        def boot(name, fuse, faults=None):
            return EngineHarness(
                name=name, graph=json.loads(json.dumps(graph)),
                registry=registry, metrics=MetricsRegistry(),
                annotations={"seldon.io/fuse": "true"} if fuse else None,
                faults=faults,
            ).start()

        plain = boot("rag-plain", fuse=False)
        fused = boot("rag-fused", fuse=True)
        chaos = boot(
            "rag-chaos", fuse=True,
            faults=FaultInjector([{"unit": "rerank", "latency_ms": 1.0}]),
        )
        try:
            rs = np.random.RandomState(5)
            reqs = [
                {"data": {"ndarray": rs.randint(1, V, (1, 8)).tolist()}}
                for _ in range(4)
            ]

            def predict(harness, req):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", harness.http_port, timeout=60
                )
                conn.request(
                    "POST", "/api/v0.1/predictions",
                    json.dumps(req).encode(),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                payload = resp.read()
                if resp.status != 200:
                    raise RuntimeError(
                        f"predict {resp.status}: {payload[:200]!r}"
                    )
                return json.loads(payload)

            plain_outs = [_scrub(predict(plain, r)) for r in reqs]
            fused_outs = [_scrub(predict(fused, r)) for r in reqs]
            check("fused == unfused (greedy tail incl.)",
                  plain_outs == fused_outs)
            check(
                "requestPath covers every stage",
                list(fused_outs[0]["meta"]["requestPath"]) == [
                    "embed", "retrieve", "rerank", "prompt", "generate",
                ],
            )

            def get(harness, path):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", harness.http_port, timeout=30
                )
                conn.request("GET", path)
                resp = conn.getresponse()
                return resp.status, resp.read().decode()

            _st, metrics = get(fused, "/metrics")
            check("fused /metrics exposes seldon_engine_fused_segments",
                  "seldon_engine_fused_segments" in metrics)
            check("clean path counts no fusion fallbacks",
                  "seldon_engine_fusion_fallbacks" not in metrics)

            st, fr_raw = get(fused, "/flightrecorder")
            check("/flightrecorder 200", st == 200)
            fr = json.loads(fr_raw)
            fusion_dump = (fr.get("units") or {}).get("(fusion)") or {}
            recs = [
                e for e in fusion_dump.get("entries", [])
                if e.get("type") == "fused_dispatch"
            ]
            check("(fusion) dump has fused_dispatch records",
                  len(recs) == len(reqs), f"{len(recs)} records")

            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            import flight_report

            rendered = flight_report.render(fr)
            check("flight_report renders fused segments",
                  "fused segment" in rendered, rendered[:200])

            chaos_outs = [_scrub(predict(chaos, r)) for r in reqs]
            check("chaos output identical per-unit", chaos_outs == plain_outs)
            _st, cmetrics = get(chaos, "/metrics")
            check(
                "chaos fallback counted",
                'seldon_engine_fusion_fallbacks' in cmetrics
                and 'reason="faults"' in cmetrics,
            )
        finally:
            plain.stop()
            fused.stop()
            chaos.stop()
            gen.close()

    print("PASS" if not failures else f"FAILED: {failures}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
