#!/usr/bin/env python3
"""CI smoke for the progressive-delivery subsystem.

Boots TWO predictor versions of the same tiny checkpoint behind real
engines on sockets (a "baseline" and a "canary"), then drives the whole
rollout surface end to end:

* a canary rollout plan applied to a real ``ResourceStore`` — the
  ``RolloutController`` starts the ramp, one analysis window of live
  greedy traffic earns a **promote** (the store's traffic weights
  actually move, byte-identical responses at both steps);
* a second rollout is breached on purpose (error traffic at the canary)
  — the controller **auto-rolls-back**, restoring baseline weights
  within one analysis interval;
* the shadow mirror duplicates live requests to a diverging target and
  the token-level differ counts the drift;
* the ``seldon_rollout_{step,verdicts,mirrors,divergence}`` series are
  asserted in the Prometheus exposition.

Run directly (``JAX_PLATFORMS=cpu python tools/rollout_smoke.py``) or
from the CI progressive-delivery step. Exits non-zero on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.controlplane import ResourceStore, SeldonDeployment
    from seldon_core_tpu.graph.engine_metrics import REGISTRY
    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.rollout import RolloutController, ShadowMirror
    from seldon_core_tpu.servers.generateserver import GenerateServer

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    def rollout_dep(name: str, steps: str) -> SeldonDeployment:
        return SeldonDeployment.from_dict({
            "name": name,
            "predictors": [
                {"name": "baseline", "traffic": 100,
                 "graph": {"name": "model", "implementation": "SIMPLE_MODEL"}},
                {"name": "canary", "traffic": 0,
                 "annotations": {
                     "seldon.io/rollout": "canary",
                     "seldon.io/rollout-steps": steps,
                     "seldon.io/rollout-interval-s": "1",
                     "seldon.io/rollout-min-samples": "2",
                     # twin engines share one CI host: TTFT/TPOT ratios
                     # are load noise there; the smoke's gate proof is
                     # the error-rate breach below
                     "seldon.io/rollout-max-ttft-ratio": "1000",
                     "seldon.io/rollout-max-tpot-ratio": "1000",
                 },
                 "graph": {"name": "model", "implementation": "SIMPLE_MODEL"}},
            ],
        })

    with tempfile.TemporaryDirectory(prefix="rollout-smoke-") as root:
        cfg = {"vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 2,
               "n_kv_heads": 2, "d_ff": 64, "max_seq": 64}
        model_dir = write_model_dir(root, "llm", cfg)

        def boot(name: str):
            c = GenerateServer(model_uri=model_dir, slots=2, steps_per_poll=4,
                               warmup_prompt_lens=[4], warmup_max_new_tokens=6)
            c.load()
            return c, EngineHarness(c, name=name).start()

        old, baseline_h = boot("baseline")  # the two predictor versions
        new, canary_h = boot("canary")
        headers = {"Content-Type": "application/json"}

        def greedy(port: int, prompt) -> list:
            conn = http.client.HTTPConnection("127.0.0.1", port)
            conn.request("POST", "/api/v0.1/predictions", json.dumps({
                "jsonData": {"prompt_tokens": [prompt], "max_new_tokens": 6,
                             "temperature": 0.0},
            }).encode(), headers)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload[:120]!r}")
            return json.loads(payload)["jsonData"]["tokens"][0]

        clock = [1000.0]
        store = ResourceStore()
        ctl = RolloutController(store, metrics=REGISTRY, now=lambda: clock[0])
        prompt = [5, 6, 7, 8]
        try:
            # -- one ramp step, gated on live traffic ---------------------
            reference = greedy(baseline_h.http_port, prompt)
            store.apply(rollout_dep("smoke-ramp", "25,100"))
            v = ctl.tick_all().get("default/smoke-ramp")
            check("rollout starts at first step", v == "start", repr(v))
            w = {p.name: p.traffic
                 for p in store.get("smoke-ramp").predictors}
            check("store weights moved to 25/75", w == {"baseline": 75, "canary": 25}, repr(w))
            for _ in range(3):  # one analysis window of canary+baseline traffic
                out_c = greedy(canary_h.http_port, prompt)
                out_b = greedy(baseline_h.http_port, prompt)
                check("canary greedy bytes identical", out_c == reference)
                check("baseline greedy bytes identical", out_b == reference)
            clock[0] += 1.0
            v = ctl.tick_all().get("default/smoke-ramp")
            check("healthy window promotes", v == "promote", repr(v))
            w = {p.name: p.traffic
                 for p in store.get("smoke-ramp").predictors}
            check("ramp advanced to 100/0", w == {"baseline": 0, "canary": 100}, repr(w))

            # -- forced gate breach -> auto-rollback ----------------------
            store.apply(rollout_dep("smoke-breach", "50,100"))
            ctl.tick_all()
            bad = list(range(1, cfg["max_seq"] + 32))  # over every bucket
            for _ in range(3):
                try:
                    greedy(canary_h.http_port, bad)
                except RuntimeError:
                    pass  # counted as a canary error at the engine
                greedy(baseline_h.http_port, prompt)
            clock[0] += 1.0
            v = ctl.tick_all().get("default/smoke-breach")
            check("gate breach rolls back", v == "rollback", repr(v))
            w = {p.name: p.traffic
                 for p in store.get("smoke-breach").predictors}
            check("rollback restored baseline weights within one interval",
                  w == {"baseline": 100, "canary": 0}, repr(w))
            trail = [e["event"] for e in ctl.events("default/smoke-breach")]
            check("event trail records start->step->rollback",
                  trail[0] == "start" and trail[-1] == "rollback", repr(trail))

            # -- shadow mirror + divergence diffing -----------------------
            mirror = ShadowMirror(
                [("canary", canary_h.app)], deployment="default/smoke-ramp",
                metrics=REGISTRY,
            )
            baseline_h.app.shadow_mirror = mirror
            greedy(baseline_h.http_port, prompt)  # identical twin: no drift

            def diverging(message):  # a canary that drifts one token
                toks = list(reference)
                toks[-1] = (toks[-1] + 1) % cfg["vocab_size"]
                return {"jsonData": {"tokens": [toks]}}

            mirror.targets = [("canary", diverging)]
            greedy(baseline_h.http_port, prompt)
            deadline = time.monotonic() + 5.0
            while mirror.counts["mirrored"] < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            check("mirror dispatched fire-and-forget",
                  mirror.counts["mirrored"] >= 2, repr(mirror.counts))
            check("differ counted exactly the drifting mirror",
                  mirror.counts["diverged"] == 1, repr(mirror.counts))
            recent = list(mirror.recent)
            check("divergence sample carries token-level detail",
                  bool(recent) and recent[0].get("kind") == "generate"
                  and recent[0].get("mismatch_tokens", 0) >= 1, repr(recent))

            # -- the seldon_rollout_* exposition --------------------------
            expo = REGISTRY.expose()
            for series in ("seldon_rollout_step", "seldon_rollout_verdicts",
                           "seldon_rollout_mirrors", "seldon_rollout_divergence"):
                check(f"exposition has {series}", series in expo)
            check("divergence counter incremented",
                  REGISTRY.counter_total("seldon_rollout_divergence",
                                         {"predictor": "canary"}) >= 1.0)
        finally:
            baseline_h.app.shadow_mirror = None
            baseline_h.stop()
            canary_h.stop()
            for c in (old, new):
                if c.batcher is not None:
                    c.batcher.close()

    if failures:
        print(f"\nrollout smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nrollout smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
