#!/usr/bin/env python3
"""CI smoke for the autonomic serving planner (operate.md §"Autonomic
planning"): profiler sweep -> SPF1 artifact -> controller tick ->
retune through the safe path, on a real engine.

Flow:

* sweeps a REAL tiny engine through a 2-point config grid under one
  seeded TrafficSim trace (``run_sweep``), asserting the SPF1 artifact
  round-trips, refuses truncation typed, and yields a monotone cost
  model;
* boots a GENERATE_SERVER deployment through the store/reconciler with
  ``seldon.io/planner`` + ``seldon.io/planner-profile`` annotations,
  drives a trafficsim burst through its scheduler, scrapes the fleet
  plane, and ticks the planner: a warn-severity burn verdict must
  actuate a retune THROUGH the safe path (``retune()`` at a poll
  boundary) — verified by re-scraping ``/fleet``'s planning block and
  by greedy byte-identity across the retune;
* asserts the ``seldon_engine_planner_retunes`` exposition and the
  controller's planner stats;
* renders the ``planner_retune`` flight records through
  ``flight_report`` — including the THRASHING DIAGNOSIS once a knob
  is flipped straight back;
* regression-checks the planner/autoscaler precedence: a page burn
  verdict VETOES a same-tick scale-down at the actuation site.

Run directly (``JAX_PLATFORMS=cpu python tools/planner_smoke.py``) or
from the CI planner_smoke step. Exits non-zero on any failure.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("SELDON_DEBUG_THREADS", "1")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

    from seldon_core_tpu.models.llm import DecoderLM
    from seldon_core_tpu.planning import (
        CostModel,
        ServingPlanner,
        TrafficSim,
        build_profile,
        read_profile,
        replay,
        run_sweep,
        sweep_grid,
        write_profile,
    )
    from seldon_core_tpu.serving.continuous import ContinuousBatcher
    from seldon_core_tpu.serving.disagg import TruncatedStream

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}"
              + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    cfg = {"vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 4,
           "n_kv_heads": 2, "d_ff": 64, "max_seq": 64}

    # -- offline: sweep a real engine into an SPF1 artifact ------------------
    model = DecoderLM(**cfg)
    params = model.init_params(0)
    sim = TrafficSim(
        seed=42, duration_s=8, base_rps=4, tenants=3, prompt_families=4,
        prefix_len=8, suffix_len=(2, 10), vocab=256,
        max_new_tokens=(4, 12), deadline_s=(2.0, 10.0), deadline_frac=0.3,
    )

    def factory(config):
        return ContinuousBatcher(
            model, params,
            slots=config["slots"],
            fused_steps_per_dispatch=config["fused_steps_per_dispatch"],
            max_seq=64, prefill_buckets=(8, 16, 32), steps_per_poll=2,
        )

    profile = run_sweep(
        factory, sweep_grid(slots=(2,), fused_steps=(0, 8)), sim,
        model_family="llm-smoke", max_events=8,
    )
    check("sweep priced every grid point", len(profile["grid"]) == 2)
    check("sweep measured real tokens",
          all(e["tokens_per_s"] > 0 for e in profile["grid"]),
          json.dumps([e["tokens_per_s"] for e in profile["grid"]]))
    check("sweep recorded the compile census",
          all(e["compile_census"]["variants"] >= 1
              and e["compile_census"]["compile_s"] > 0
              for e in profile["grid"]))

    with tempfile.TemporaryDirectory(prefix="planner-smoke-") as root:
        swept = os.path.join(root, "swept.spf1")
        write_profile(swept, profile)
        check("SPF1 artifact round-trips", read_profile(swept) == profile)
        try:
            with open(swept, "rb") as f:
                from seldon_core_tpu.planning import decode_profile

                decode_profile(f.read()[:-4])
            check("truncated SPF1 refuses typed", False, "decoded!")
        except TruncatedStream:
            check("truncated SPF1 refuses typed", True)
        cm = CostModel(profile)
        preds = [
            cm.predict({"slots": 2, "fused_steps_per_dispatch": k})
            ["tokens_per_s"]
            for k in (0, 2, 8, 32)
        ]
        check("cost model monotone in fused K", preds == sorted(preds),
              json.dumps([round(p, 1) for p in preds]))

        # the closed-loop leg plans over a DETERMINISTIC profile (the
        # swept numbers above are real but noisy on shared CI chips):
        # fused=8 breaches the warn objective, fused=4 meets it, so the
        # decision table must pick the 8 -> 4 retune
        plan_profile = os.path.join(root, "plan.spf1")
        write_profile(plan_profile, build_profile("llm-smoke", [
            {"config": {"slots": 2, "prefill_chunk": 0,
                        "fused_steps_per_dispatch": 8, "depth_groups": 0,
                        "depth_group_split_bytes": 0, "kv_tier_bytes": 0},
             "tokens_per_s": 200.0, "ttft_p50_ms": 400.0,
             "ttft_p99_ms": 900.0, "tpot_p50_ms": 30.0,
             "tpot_p99_ms": 60.0, "hbm_bytes": 10**9},
            {"config": {"slots": 2, "prefill_chunk": 0,
                        "fused_steps_per_dispatch": 4, "depth_groups": 0,
                        "depth_group_split_bytes": 0, "kv_tier_bytes": 0},
             "tokens_per_s": 300.0, "ttft_p50_ms": 120.0,
             "ttft_p99_ms": 250.0, "tpot_p50_ms": 8.0,
             "tpot_p99_ms": 15.0, "hbm_bytes": 10**9},
        ]))

        model_dir = os.path.join(root, "llm")
        os.makedirs(model_dir)
        with open(os.path.join(model_dir, "jax_config.json"), "w") as f:
            json.dump({"family": "llm", "config": {**cfg, "seed": 0}}, f)

        asyncio.run(closed_loop(check, model_dir, plan_profile, sim))

    if failures:
        print(f"\nplanner smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nplanner smoke passed")
    return 0


async def closed_loop(check, model_dir, profile_path, sim) -> None:
    import importlib.util

    from seldon_core_tpu.controlplane.reconciler import DeploymentController
    from seldon_core_tpu.controlplane.resource import SeldonDeployment
    from seldon_core_tpu.controlplane.store import ResourceStore
    from seldon_core_tpu.graph.engine_metrics import REGISTRY
    from seldon_core_tpu.planning import Decision, replay

    store = ResourceStore()
    ctl = DeploymentController(store)
    dep, _ = store.apply(SeldonDeployment.from_dict({
        "metadata": {"name": "gen", "namespace": "default"},
        "spec": {"predictors": [{
            "name": "main",
            "replicas": 1,
            "annotations": {
                "seldon.io/planner": "true",
                "seldon.io/planner-profile": profile_path,
            },
            "graph": {
                "name": "llm", "implementation": "GENERATE_SERVER",
                "modelUri": model_dir,
                "parameters": [
                    {"name": "slots", "value": "2", "type": "INT"},
                    {"name": "max_seq", "value": "64", "type": "INT"},
                    {"name": "steps_per_poll", "value": "2", "type": "INT"},
                    {"name": "fused_steps_per_dispatch", "value": "8",
                     "type": "INT"},
                ],
            },
        }]},
    }))
    status = await ctl.reconcile(dep.clone())
    check("planner-annotated deployment reconciles",
          status.state == "Available", status.description or "")

    try:
        # the live GenerateServer unit behind the in-process handle
        srv = None
        for handle, _ in ctl.components.values():
            for _name, target in handle.app.units_with("serving_config"):
                srv = target
        check("engine unit found", srv is not None)

        # greedy references BEFORE any retune — identity must hold across
        prompts = [[3, 17, 42, 99], [9, 8, 7], [1, 2, 3, 4, 5]]
        refs = [srv.batcher.generate(p, max_new_tokens=8) for p in prompts]

        # a trafficsim burst through the scheduler (SLO samples + load)
        trace = sim.trace(max_events=10)
        handles = replay(
            trace,
            lambda ev: srv.batcher.submit(
                ev.prompt, max_new_tokens=ev.max_new_tokens,
                tenant=ev.tenant, deadline_s=ev.deadline_s,
            ),
        )
        served = sum(1 for h in handles if h.result(timeout=120) is not None)
        check("trafficsim burst served", served == len(trace),
              f"{served}/{len(trace)}")

        # fleet scrape: the planner's ONLY telemetry source
        await ctl.fleet_scrape_once()
        plan_blocks = [
            unit.get("planning")
            for units in ctl._fleet_units.values()
            for unit in units.values()
            if unit.get("planning")
        ]
        check("/fleet carries the planning block",
              bool(plan_blocks)
              and plan_blocks[0]["config"]["fused_steps_per_dispatch"] == 8
              and 4 in plan_blocks[0]["census"]["fused_ks"],
              json.dumps(plan_blocks[:1]))

        # warn-severity burn (what the scrape would accumulate during a
        # storm) -> the decision table must retune 8 -> 4 via the profile
        ctl._burn_verdicts[(dep.key, "main")] = [
            {"slo": "ttft_p99", "severity": "warn", "threshold_s": 0.5},
        ]
        events = await ctl.planner_tick_once()
        ev = events.get(f"{dep.key}/main") or {}
        check("planner tick decided a retune",
              ev.get("action") == "retune"
              and ev.get("knobs") == {"fused_steps_per_dispatch": 4}
              and ev.get("retuned", 0) >= 1,
              json.dumps(ev))

        # the knob actually moved, observed through the SAME fleet plane
        await ctl.fleet_scrape_once()
        cfgs = [
            unit["planning"]["config"]["fused_steps_per_dispatch"]
            for units in ctl._fleet_units.values()
            for unit in units.values()
            if unit.get("planning")
        ]
        check("retune landed at the poll boundary", cfgs == [4],
              json.dumps(cfgs))
        check("controller counted the retune",
              ctl.fleet_summary()["planner"]["stats"]["retunes"] == 1)

        # byte identity across the live retune — greedy streams unchanged
        got = [srv.batcher.generate(p, max_new_tokens=8) for p in prompts]
        check("greedy byte-identical across retune", got == refs)

        # exposition: the planner series rides the recovery-metric path
        REGISTRY.record_custom(srv.metrics())
        expo = REGISTRY.expose()
        check("exposition has seldon_engine_planner_retunes",
              "seldon_engine_planner_retunes" in expo)

        # flip the knob straight back and forth: flight_report must
        # render the planner_retune records AND diagnose the thrash
        for handle, _ in ctl.components.values():
            await handle.retune({"fused_steps_per_dispatch": 8})
            await handle.retune({"fused_steps_per_dispatch": 4})
        fr = os.path.join(os.path.dirname(__file__), "flight_report.py")
        spec = importlib.util.spec_from_file_location("flight_report", fr)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        text = mod.render(srv.flight_dump())
        check("flight report renders planner retunes",
              "planner retunes: 3 applied at poll boundaries" in text,
              text.splitlines()[0] if text else "")
        check("flight report diagnoses retune thrash",
              "THRASHING" in text and "fused_steps_per_dispatch" in text)

        # precedence regression: page burn vetoes a same-tick scale-down
        ctl._burn_verdicts[(dep.key, "main")] = [
            {"slo": "ttft_p99", "severity": "page"},
        ]
        out = await ctl._planner_actuate(
            dep, dep.predictors[0], Decision("scale_down", "idle", rank=6)
        )
        check("page burn vetoes planner scale-down",
              out == {"vetoed": True}
              and ctl.planner_stats["vetoes"] == 1
              and store.get("gen").predictors[0].replicas == 1,
              json.dumps(out))
    finally:
        await ctl.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
