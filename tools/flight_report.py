#!/usr/bin/env python3
"""Turn a scheduler flight-recorder dump into a human-readable diagnosis.

Input: the JSON served at the engine's ``/flightrecorder`` route (either
the full ``{"units": {name: dump}}`` payload or one unit's dump), from a
file argument or stdin (``-``). Output: a per-unit report attributing
where generation time is going — queue wait vs first-token latency vs
decode pacing — plus what the scheduler actually decided poll by poll
(depth-group splits and cost-model merges, chunked-prefill interleave,
prefix-cache hits, shed events).

Usage::

    curl -s localhost:8000/flightrecorder | python tools/flight_report.py -
    python tools/flight_report.py dump.json
    python tools/flight_report.py --json dump.json   # machine-readable:
    # {unit: {"lines": [...], "diagnosis": [DIAGNOSIS subset]}}
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List


def _pct(n: float, d: float) -> float:
    return 100.0 * n / d if d else 0.0


def _swap_lines(swaps: List[Dict[str, Any]]) -> List[str]:
    """Weight hot-swap records, shown inline with the scheduling story:
    each flip names the version pair and how long the drain held the
    poll loop (lanes in flight when staged, polls spent waiting)."""
    lines: List[str] = []
    for s in swaps:
        lines.append(
            f"weight swap: {s.get('old_version')!r} -> "
            f"{s.get('new_version')!r} after draining "
            f"{s.get('drained_lanes', 0)} in-flight lanes over "
            f"{s.get('waited_polls', 0)} polls (prefix cache re-keyed)"
        )
    if len(swaps) > 1:
        lines.append(
            f"DIAGNOSIS: {len(swaps)} weight swaps inside one ring window — "
            "each flip purges the prefix cache and pauses admissions for "
            "the drain; batch rollouts should space swaps out"
        )
    return lines


def _kv_lines(
    exports: List[Dict[str, Any]], inserts: List[Dict[str, Any]]
) -> List[str]:
    """Disaggregated-serving records, shown inline with the scheduling
    story: slab exports (prefill pool) and remote inserts (decode pool)
    with their transfer-dedup coverage, so a dump from either pool shows
    which half of the handoff this scheduler is and what crossed the
    wire."""
    lines: List[str] = []
    if exports:
        total = sum(e.get("bytes", 0) for e in exports)
        suffix_only = [e for e in exports if e.get("covered_len", 0) > 0]
        chunked = [e for e in exports if e.get("chunks", 0) > 1]
        lines.append(
            f"kv export (prefill pool): {len(exports)} slabs, "
            f"{total / 1e6:.2f} MB shipped; {len(suffix_only)} suffix-only "
            f"(decode-side prefix cache deduplicated the rest), "
            f"{len(chunked)} built via chunked staging"
        )
    if inserts:
        total = sum(e.get("bytes", 0) for e in inserts)
        dedup = [e for e in inserts if e.get("covered_len", 0) > 0]
        saved_toks = sum(e.get("covered_len", 0) for e in inserts)
        lines.append(
            f"remote inserts (decode pool): {len(inserts)} slabs spliced, "
            f"{total / 1e6:.2f} MB received; {len(dedup)} rode a local "
            f"prefix hit ({saved_toks} prompt tokens never crossed the "
            "wire)"
        )
        if inserts and not dedup:
            lines.append(
                "DIAGNOSIS: every remote insert shipped its full slab — "
                "no decode-side prefix hits; if traffic shares prompts, "
                "set prefix_cache_hbm_bytes on the DECODE pool (it is "
                "the transfer-dedup layer)"
            )
    return lines


def _fault_lines(
    restarts: List[Dict[str, Any]],
    ejects: List[Dict[str, Any]],
    readmits: List[Dict[str, Any]],
    degraded: List[Dict[str, Any]],
) -> List[str]:
    """Fault-tolerance records, shown inline with the scheduling story:
    supervised batcher restarts, prefill-peer ejections/readmissions and
    local-prefill degradation — the diagnosis trail of a chaotic run."""
    lines: List[str] = []
    if restarts:
        latched = [r for r in restarts if r.get("outcome") == "latched_dead"]
        lines.append(
            f"scheduler supervision: {len(restarts)} loop death(s) — "
            + ", ".join(
                f"attempt {r.get('attempt')}/{r.get('budget')} "
                f"({r.get('outcome')}, backoff {r.get('backoff_s')}s)"
                for r in restarts
            )
        )
        if latched:
            lines.append(
                "DIAGNOSIS: the crash-loop budget is EXHAUSTED — this "
                "member is latched unready and will only recover by "
                "replacement; look at the paired loop-death tracebacks "
                "in the server log"
            )
        elif len(restarts) > 1:
            lines.append(
                "DIAGNOSIS: repeated loop deaths inside one ring window "
                "— the fault is recurring, not transient; each restart "
                "pays a cache rebuild + re-warm and fails every "
                "in-flight request"
            )
    if ejects:
        peers: Dict[str, int] = {}
        for e in ejects:
            peers[e.get("peer", "?")] = peers.get(e.get("peer", "?"), 0) + 1
        lines.append(
            "prefill-peer failover: "
            + ", ".join(f"{p} ejected {n}x" for p, n in sorted(peers.items()))
            + f"; {len(readmits)} readmission(s)"
        )
    if degraded:
        lines.append(
            f"degraded local prefill: {len(degraded)} remote prefills "
            "served LOCALLY (entire prefill pool ejected) — decode kept "
            "answering, but the isolation win is suspended"
        )
        lines.append(
            "DIAGNOSIS: the decode pool is doing prefill work; check "
            "the prefill listeners (seldon_engine_peer_ejections) and "
            "expect TTFT isolation to regress until readmission"
        )
    return lines


def _pressure_lines(
    preempts: List[Dict[str, Any]],
    resumes: List[Dict[str, Any]],
    reclaims: List[Dict[str, Any]],
    budgets: List[Dict[str, Any]],
    pressure: Dict[str, Any],
) -> List[str]:
    """HBM-pressure records, shown inline with the scheduling story:
    ledger re-budgets, reclaim-ladder rungs, decode-lane preemptions and
    their recompute-resumes — the trail of an overload window."""
    lines: List[str] = []
    if budgets:
        lines.append(
            f"pressure budget: {len(budgets)} re-budget(s) — now "
            f"{budgets[-1].get('budget_bytes', 0) / 1e6:.2f} MB"
            + (" (restored)" if budgets[-1].get("restored") else "")
        )
    evicts = [r for r in reclaims if r.get("action") == "evict_prefix"]
    spec_off = [r for r in reclaims if r.get("action") == "cancel_speculation"]
    if evicts:
        lines.append(
            f"pressure reclaim: {sum(r.get('evicted', 0) for r in evicts)} "
            f"prefix slab(s) evicted across {len(evicts)} ladder pass(es)"
        )
    if spec_off:
        lines.append(
            "pressure reclaim: speculation cancelled (draft cache freed) "
            f"{len(spec_off)}x"
        )
    if preempts:
        lanes = [p for p in preempts if p.get("kind") != "chunked"]
        chunked = [p for p in preempts if p.get("kind") == "chunked"]
        recompute = sum(p.get("emitted", 0) for p in lanes)
        lines.append(
            f"decode-lane preemption: {len(lanes)} lane(s) checkpointed "
            f"to host ({recompute} generated tokens to recompute), "
            f"{len(chunked)} chunked admission(s) requeued; "
            f"{len(resumes)} recompute-resume(s) landed"
        )
        # only checkpoint-carrying preemptions produce preempt_resume
        # records (a zero-emitted or chunked victim requeues whole and
        # re-enters through the plain admit path) — comparing against
        # ALL preempts would cry wolf on a healthy run
        checkpointed = [p for p in preempts if p.get("emitted", 0) > 0]
        if len(resumes) < len(checkpointed):
            lines.append(
                "DIAGNOSIS: preempted requests are still waiting to "
                "resume — the ledger has not cleared its low watermark; "
                "if this persists, the budget is too small for even one "
                "lane of this depth (raise hbm_ledger_bytes)"
            )
        else:
            lines.append(
                "DIAGNOSIS: every preemption resumed — output stays "
                "byte-identical (recompute-resume continues the exact "
                "sampling stream); the cost was the recomputed prefill "
                "plus the wait, visible as TTFT/TPOT inflation in the "
                "SLO block above"
            )
    if pressure:
        used = pressure.get("used_bytes", 0)
        budget = pressure.get("budget_bytes", 0)
        state = "ACTIVE" if pressure.get("active") else "clear"
        comp = pressure.get("components") or {}
        comp_txt = ", ".join(
            f"{k} {v / 1e6:.2f}" for k, v in sorted(comp.items()) if v
        ) or "idle"
        lines.append(
            f"pressure ledger: {used / 1e6:.2f} of {budget / 1e6:.2f} MB "
            f"({state}; MB by component: {comp_txt})"
        )
    return lines


def _tier_lines(
    demotes: List[Dict[str, Any]],
    promotes: List[Dict[str, Any]],
    tier_hits: List[Dict[str, Any]],
    tier: Dict[str, Any],
) -> List[str]:
    """Host-KV-tier records, shown inline with the scheduling story:
    demotions (prefix slabs + lane checkpoints spilled to host RAM),
    promotions back to device, and tier hits (local match, peer lookup
    served, checkpoint copy-back) — plus a THRASH diagnosis when the
    same slab keeps bouncing between HBM and the tier."""
    lines: List[str] = []
    if demotes:
        prefixes = [d for d in demotes if d.get("kind") == "prefix"]
        ckpts = [d for d in demotes if d.get("kind") == "ckpt"]
        total = sum(d.get("bytes", 0) for d in demotes)
        lines.append(
            f"kv tier demotions: {len(prefixes)} prefix slab(s) + "
            f"{len(ckpts)} lane checkpoint(s) spilled to host RAM "
            f"({total / 1e6:.2f} MB)"
        )
    if promotes:
        copybacks = [p for p in promotes if p.get("kind") == "ckpt"]
        peer = [p for p in promotes if p.get("source") == "peer"]
        lines.append(
            f"kv tier promotions: {len(promotes)} slab(s) back to device "
            f"({len(copybacks)} copy-back resume(s), {len(peer)} pulled "
            "from a peer's tier)"
        )
    if tier_hits:
        served_peers = [h for h in tier_hits if h.get("source") == "peer"]
        if served_peers:
            lines.append(
                f"kv tier peer lookups: {len(served_peers)} prefix(es) "
                "served to peers from this member's host tier"
            )
    # thrash: the SAME slab (by prompt-hash) demoted AND promoted
    # repeatedly inside one ring window — each cycle pays a PCIe round
    # trip that a wider watermark gap would have avoided
    cycles: Dict[str, List[int]] = {}
    for d in demotes:
        if d.get("phash"):
            cycles.setdefault(d["phash"], [0, 0])[0] += 1
    for p in promotes:
        if p.get("phash"):
            cycles.setdefault(p["phash"], [0, 0])[1] += 1
    thrashing = [
        (ph, c) for ph, c in cycles.items() if c[0] >= 2 and c[1] >= 2
    ]
    if thrashing:
        worst = max(thrashing, key=lambda t: min(t[1]))
        lines.append(
            f"DIAGNOSIS: kv tier THRASH — {len(thrashing)} slab(s) "
            f"demoted→promoted repeatedly (worst {worst[0]}: "
            f"{worst[1][0]} demotions / {worst[1][1]} promotions in one "
            "ring window); the ledger is re-tripping its high watermark "
            "right after reclaim — widen the pressure_high/pressure_low "
            "gap (or raise hbm_ledger_bytes) so a promoted slab fits "
            "inside it"
        )
    if tier:
        lines.append(
            f"kv tier: {tier.get('used_bytes', 0) / 1e6:.2f} of "
            f"{tier.get('budget_bytes', 0) / 1e6:.2f} MB host RAM "
            f"({tier.get('prefix_entries', 0)} prefix entries, "
            f"{tier.get('ckpt_entries', 0)} checkpoint(s); "
            f"{tier.get('evictions', 0)} eviction(s))"
        )
    return lines


def _migration_lines(
    drains: List[Dict[str, Any]],
    exports: List[Dict[str, Any]],
    migrated: List[Dict[str, Any]],
    swap_preempts: List[Dict[str, Any]],
) -> List[str]:
    """Live-migration records, shown inline with the scheduling story:
    graceful drains (lanes checkpointed at a poll boundary), SGC1
    checkpoint exports, and migrated resumes — on the SOURCE a
    ``migrated_resume`` record carries ``handed`` (checkpoints the peer
    accepted); on the PEER each resumed checkpoint records one."""
    lines: List[str] = []
    for d in drains:
        lines.append(
            f"graceful drain: {d.get('lanes', 0)} lane(s) checkpointed "
            f"({d.get('checkpoints', 0)} with emitted tokens), "
            f"{d.get('chunked', 0)} chunked admission(s), "
            f"{d.get('handed', 0)} request(s) handed to migration"
        )
    resumed = sum(r.get("handed", 1) for r in migrated)
    if exports:
        lines.append(
            f"checkpoint export: {len(exports)} SGC1 checkpoint(s) "
            f"({sum(e.get('emitted', 0) for e in exports)} emitted "
            f"tokens carried); {resumed} resumed at/confirmed by a peer"
        )
        if len(exports) > resumed:
            lines.append(
                f"DIAGNOSIS: {len(exports) - resumed} exported "
                "checkpoint(s) have no peer resume — the drain stranded "
                "work (peer refused the weight_version, or the handoff "
                "failed); those requests failed typed instead of "
                "migrating"
            )
    elif migrated:
        lines.append(
            f"migrated resumes: {resumed} checkpoint(s) resumed here "
            "(crediting continues after each checkpoint — no span "
            "re-sent)"
        )
    for sp in swap_preempts:
        lines.append(
            f"weight-swap straggler bound: {sp.get('lanes', 0)} lane(s) "
            f"preempt-checkpointed after swap_drain_ms="
            f"{sp.get('swap_drain_ms')} (policy {sp.get('policy')!r})"
        )
    return lines


def _pager_lines(
    page_ins: List[Dict[str, Any]],
    page_outs: List[Dict[str, Any]],
    switches: List[Dict[str, Any]],
    pager: Dict[str, Any],
    sched: Dict[str, Any],
) -> List[str]:
    """Multi-tenant weight-pager records (generate.md §13): page-in /
    page-out cycles and tenant switches, plus a THRASH diagnosis when
    tenants keep displacing each other inside one ring window — every
    such cycle pays a host→HBM upload + swap drain that a longer
    residency would have amortized."""
    lines: List[str] = []
    if switches:
        forced = [s for s in switches if s.get("forced")]
        costs = [s["cost_ms"] for s in switches if "cost_ms" in s]
        avg_cost = sum(costs) / len(costs) if costs else 0.0
        lines.append(
            f"tenant switches: {len(switches)} flip(s) "
            f"({len(forced)} forced by the starvation bound), "
            f"avg page-in cost {avg_cost:.1f}ms"
        )
    if page_ins or page_outs:
        lines.append(
            f"weight pager: {len(page_ins)} page-in(s), "
            f"{len(page_outs)} page-out(s) in the recorded window"
        )
    # thrash: two or more tenants each paged IN repeatedly inside one
    # ring window — the working set is alternating faster than
    # residency amortizes, so throughput tracks page-in bandwidth
    per_tenant: Dict[str, int] = {}
    for p in page_ins:
        t = p.get("tenant")
        if t:
            per_tenant[t] = per_tenant.get(t, 0) + 1
    cyclers = {t: n for t, n in per_tenant.items() if n >= 2}
    if len(cyclers) >= 2:
        worst = max(cyclers.items(), key=lambda kv: kv[1])
        lines.append(
            f"DIAGNOSIS: weight pager THRASH — {len(cyclers)} tenant(s) "
            f"paged in repeatedly (worst {worst[0]!r}: {worst[1]} "
            "page-ins in one ring window); each cycle pays drain + "
            "host→HBM upload — raise tenant_min_resident_ms so the "
            "batch-deeper rule amortizes residency, or give hot "
            "tenants a dedicated member"
        )
    if pager:
        lines.append(
            f"weight pager staging: {pager.get('host_bytes', 0) / 1e6:.2f} "
            f"of {pager.get('budget_bytes', 0) / 1e6:.2f} MB host RAM "
            f"({len(pager.get('tenants') or [])} tenant(s), resident "
            f"{pager.get('resident')!r}; {pager.get('evictions', 0)} "
            f"eviction(s), {pager.get('refused', 0)} refusal(s), "
            f"{pager.get('corrupt_dropped', 0)} corrupt drop(s))"
        )
    if sched:
        queued = sched.get("queued") or {}
        if queued:
            lines.append(
                "tenant queues at dump time: "
                + ", ".join(f"{t}={n}" for t, n in sorted(queued.items()))
            )
    return lines


def _fusion_lines(
    dispatches: List[Dict[str, Any]],
    fallbacks: List[Dict[str, Any]],
    segments: Dict[str, Any],
) -> List[str]:
    """Graph-fusion records (the executor's ``(fusion)`` pseudo-unit):
    fused-segment dispatches vs counted fallbacks to the per-unit walk,
    with a DIAGNOSIS when the fallback rate says fusion is configured
    but barely serving."""
    lines: List[str] = []
    if not dispatches and not fallbacks and not segments:
        return lines
    for name, seg in sorted(segments.items()):
        stages = seg.get("stages") or []
        lines.append(
            f"fused segment {name}: {' -> '.join(stages)} "
            f"({seg.get('kind', '?')}, {len(stages)} stages -> 1 dispatch): "
            f"{seg.get('dispatches', 0)} dispatch(es), fallbacks "
            f"{seg.get('fallbacks') or {}}"
        )
    if dispatches:
        durs = sorted(e.get("dur_ms", 0.0) for e in dispatches)
        lines.append(
            f"fused dispatches in window: {len(dispatches)}, "
            f"p50 {durs[len(durs) // 2]:.2f} ms"
        )
    if fallbacks:
        # first-occurrence markers only (the ring is protected from
        # per-request flooding); cumulative counts live on the segments
        plan_reasons = sorted({
            f.get("reason", "?") for f in fallbacks
            if f.get("reason") in ("remote", "faults", "microbatch", "hedge")
        })
        if plan_reasons:
            lines.append(
                "fusion plan-time exclusions: "
                + ", ".join(plan_reasons)
                + " (per-unit semantics kept those units on the "
                "hop-by-hop path)"
            )
    # the fallback RATE comes from the cumulative per-segment totals:
    # every per-request fallback lands on its segment's counter, while
    # plan-time exclusions (structure, not traffic) never do — so the
    # rate cannot false-alarm a low-traffic window
    total_disp = sum(s.get("dispatches", 0) for s in segments.values())
    req_reasons: Dict[str, int] = {}
    for seg in segments.values():
        for r, n in (seg.get("fallbacks") or {}).items():
            req_reasons[r] = req_reasons.get(r, 0) + n
    total_fb = sum(req_reasons.values())
    if req_reasons:
        lines.append(
            "fusion fallbacks (cumulative): "
            + ", ".join(f"{n}x {r}" for r, n in sorted(req_reasons.items()))
        )
        rate = _pct(total_fb, total_disp + total_fb)
        if rate >= 50.0:
            dominant = max(req_reasons.items(), key=lambda kv: kv[1])[0]
            hint = {
                "deadline": "deadline-carrying traffic always takes the "
                "per-unit path — fusion buys this workload nothing",
                "shadow": "a live shadow rollout inhibits fusion; expected "
                "until the rollout goes terminal",
                "breaker_open": "an interior unit's breaker is open — fix "
                "the sick unit, fusion resumes with it",
            }.get(dominant, "look at the per-reason records above")
            lines.append(
                f"DIAGNOSIS: {rate:.0f}% of fusable requests FELL BACK to "
                f"hop-by-hop (dominant reason: {dominant}) — the compiled "
                f"segments are mostly idle; {hint}"
            )
    return lines


def _planner_lines(retunes: List[Dict[str, Any]]) -> List[str]:
    """Autonomic planner ``planner_retune`` records (operate.md
    §"Autonomic planning"): knob changes the scheduler applied at poll
    boundaries — with a DIAGNOSIS when the controller is thrashing (the
    same knob rewritten over and over, or flipped straight back) rather
    than converging."""
    if not retunes:
        return []
    lines: List[str] = []
    knob_counts: Dict[str, int] = {}
    for r in retunes:
        for knob in (r.get("changed") or {}):
            knob_counts[knob] = knob_counts.get(knob, 0) + 1
    deferred = sum(1 for r in retunes if r.get("waited_polls"))
    knob_txt = ", ".join(
        f"{k} x{n}" for k, n in sorted(knob_counts.items())
    ) or "no knobs changed"
    lines.append(
        f"planner retunes: {len(retunes)} applied at poll boundaries "
        f"({knob_txt})"
        + (
            f"; {deferred} deferred for in-flight chunked prefills"
            if deferred else ""
        )
    )
    thrash = []
    for knob, n in sorted(knob_counts.items()):
        trans = [
            tuple(r["changed"][knob]) for r in retunes
            if knob in (r.get("changed") or {})
        ]
        reverted = any(
            trans[j][1] == trans[i][0]
            for i in range(len(trans))
            for j in range(i + 1, len(trans))
        )
        if n >= 3 or (n >= 2 and reverted):
            thrash.append(knob)
    if thrash:
        lines.append(
            f"DIAGNOSIS: planner retunes are THRASHING on "
            f"{', '.join(thrash)} — the same knob keeps being rewritten "
            "inside one ring window, so the decision table is "
            "oscillating between configs instead of converging; raise "
            "the planner's retune cooldown, or re-profile (two grid "
            "points are priced closer than the live noise)"
        )
    return lines


def _device_time_lines(
    polls: List[Dict[str, Any]],
    profiler: Dict[str, Any],
    slo_burn: Dict[str, Any],
) -> List[str]:
    """Device-time ledger + SLO burn records (operate.md §4): per-poll
    ``device_time`` rows aggregated by executable kind over the recorded
    window, the cumulative ledger summary with its live gauges, and the
    burn-rate verdicts — with a DIAGNOSIS when one executable kind
    dominates >80% of the window's attributed device time."""
    lines: List[str] = []
    # window view: the per-poll deltas that rode the ring
    by_kind: Dict[str, List[float]] = {}
    for p in polls:
        for row in p.get("device_time") or []:
            agg = by_kind.setdefault(row.get("kind", "?"), [0.0, 0.0, 0.0])
            agg[0] += row.get("s", 0.0)
            agg[1] += row.get("n", 0)
            agg[2] += row.get("bytes", 0)
    total_s = sum(v[0] for v in by_kind.values())
    if by_kind:
        parts = ", ".join(
            f"{k} {_pct(v[0], total_s):.0f}% ({int(v[1])} disp)"
            for k, v in sorted(
                by_kind.items(), key=lambda kv: -kv[1][0]
            )
        )
        lines.append(
            f"device-time window: {total_s * 1e3:.1f} ms attributed "
            f"across {len(by_kind)} kind(s) — {parts}"
        )
        dominant, agg = max(by_kind.items(), key=lambda kv: kv[1][0])
        share = _pct(agg[0], total_s)
        if share > 80.0 and len(by_kind) > 1:
            hint = {
                "prefill": "admissions dominate — look at chunked "
                "prefill / prefix caching to take prompt work off the "
                "serving path",
                "decode_burst": "plain decode bursts dominate — fused "
                "decode (decode_fuse_steps) cuts their dispatch floor",
                "fused_burst": "expected shape for a healthy fused "
                "decode workload",
                "swap_cast": "weight swaps dominate — space rollouts "
                "out; each cast walks every parameter",
                "splice": "KV splices dominate — prefix-cache hit "
                "tokens are being re-spliced every admit; check hit "
                "lengths vs prompt lengths",
            }.get(dominant, "see the kind's dispatch sites in "
                  "serving/continuous.py")
        elif share > 80.0:
            hint = "single-kind window (one-shape workload)"
        if share > 80.0:
            lines.append(
                f"DIAGNOSIS: executable kind '{dominant}' consumed "
                f"{share:.0f}% of attributed device time this window — "
                f"{hint}"
            )
    if profiler:
        gauges = []
        if "device_busy_frac" in profiler:
            gauges.append(f"busy {profiler['device_busy_frac'] * 100:.1f}%")
        if "mbu_pct" in profiler:
            gauges.append(f"MBU {profiler['mbu_pct']:.1f}%")
        if "dispatch_floor_pct" in profiler:
            gauges.append(
                f"dispatch floor {profiler['dispatch_floor_pct']:.1f}%"
            )
        lines.append(
            f"device-time ledger (cumulative): "
            f"{profiler.get('device_time_s', 0.0) * 1e3:.1f} ms over "
            f"{len(profiler.get('buckets') or {})} (kind,variant,tenant) "
            f"bucket(s), {profiler.get('deep_samples', 0)} deep sample(s)"
            + ("; " + ", ".join(gauges) if gauges else "")
        )
    if slo_burn:
        for v in slo_burn.get("verdicts") or []:
            if v.get("severity") in ("warn", "page"):
                who = f" tenant {v['tenant']!r}" if v.get("tenant") else ""
                lines.append(
                    f"SLO burn {v['severity'].upper()}:{who} "
                    f"{v.get('slo')} burning "
                    f"{v.get('fast_burn', 0):.1f}x budget (fast) / "
                    f"{v.get('slow_burn', 0):.1f}x (slow), "
                    f"{v.get('budget_remaining', 0) * 100:.0f}% of the "
                    "error budget left"
                )
                if v["severity"] == "page":
                    lines.append(
                        "DIAGNOSIS: both burn windows exceed the page "
                        "rate — the error budget will exhaust within "
                        "hours at this rate; the deployment controller "
                        "is already vetoing scale-down and applying "
                        "scale-up pressure"
                    )
    return lines


def diagnose(dump: Dict[str, Any]) -> List[str]:
    """Report lines for one unit's flight-recorder dump."""
    lines: List[str] = []
    entries = dump.get("entries") or []
    polls = [e for e in entries if e.get("type") == "poll"]
    sheds = [e for e in entries if e.get("type") == "shed"]
    preempts = [e for e in entries if e.get("type") == "preempt"]
    resumes = [e for e in entries if e.get("type") == "preempt_resume"]
    reclaims = [e for e in entries if e.get("type") == "pressure_reclaim"]
    budgets = [e for e in entries if e.get("type") == "pressure_budget"]
    swaps = [e for e in entries if e.get("type") == "weight_swap"]
    drains = [e for e in entries if e.get("type") == "drain"]
    ck_exports = [
        e for e in entries if e.get("type") == "checkpoint_export"
    ]
    migrated = [e for e in entries if e.get("type") == "migrated_resume"]
    swap_preempts = [
        e for e in entries if e.get("type") == "swap_straggler_preempt"
    ]
    kv_exports = [e for e in entries if e.get("type") == "kv_export"]
    kv_inserts = [e for e in entries if e.get("type") == "remote_insert"]
    kv_demotes = [e for e in entries if e.get("type") == "kv_demote"]
    kv_promotes = [e for e in entries if e.get("type") == "kv_promote"]
    tier_hits = [e for e in entries if e.get("type") == "tier_hit"]
    restarts = [e for e in entries if e.get("type") == "batcher_restart"]
    ejects = [e for e in entries if e.get("type") == "peer_ejected"]
    readmits = [e for e in entries if e.get("type") == "peer_readmitted"]
    degraded = [
        e for e in entries if e.get("type") == "degraded_local_prefill"
    ]
    fused_disp = [e for e in entries if e.get("type") == "fused_dispatch"]
    fused_fb = [e for e in entries if e.get("type") == "fusion_fallback"]
    page_ins = [e for e in entries if e.get("type") == "weight_page_in"]
    page_outs = [e for e in entries if e.get("type") == "weight_page_out"]
    tenant_switches = [
        e for e in entries if e.get("type") == "tenant_switch"
    ]
    planner_retunes = [
        e for e in entries if e.get("type") == "planner_retune"
    ]
    lines.append(
        f"recorded {dump.get('recorded_total', len(entries))} records "
        f"(ring holds {len(entries)}, dropped "
        f"{dump.get('dropped', 0)} oldest)"
    )
    if "segments" in dump:
        # the executor's (fusion) pseudo-unit: no scheduler, no SLO
        # reservoir — its whole story is the dispatch/fallback stream
        lines.extend(_fusion_lines(
            fused_disp, fused_fb, dump.get("segments") or {}
        ))
        return lines

    # -- SLO attribution ----------------------------------------------------
    slo = dump.get("slo")
    if slo:
        qw, ttft, tpot = slo["queue_wait_ms"], slo["ttft_ms"], slo["tpot_ms"]
        # tpot is None when every completion was single-token (no
        # inter-token interval exists)
        tpot_txt = (
            f"TPOT p50 {tpot['p50_ms']}ms / p99 {tpot['p99_ms']}ms"
            if tpot else "TPOT n/a (single-token completions)"
        )
        lines.append(
            f"SLO over {slo['samples']} completed requests: "
            f"queue wait p50 {qw['p50_ms']}ms / p99 {qw['p99_ms']}ms, "
            f"TTFT p50 {ttft['p50_ms']}ms / p99 {ttft['p99_ms']}ms, "
            f"{tpot_txt}"
        )
        # what dominates the tail: the wait before a lane, or the work on it
        prefill_p99 = max(0.0, ttft["p99_ms"] - qw["p99_ms"])
        if ttft["p99_ms"] > 0:
            if qw["p99_ms"] >= 0.5 * ttft["p99_ms"]:
                lines.append(
                    f"DIAGNOSIS: p99 TTFT dominated by QUEUE WAIT "
                    f"({_pct(qw['p99_ms'], ttft['p99_ms']):.0f}% of it) — "
                    "add lanes/chips or shed earlier; the scheduler is not "
                    "the bottleneck"
                )
            else:
                lines.append(
                    f"DIAGNOSIS: p99 TTFT dominated by ADMIT+PREFILL "
                    f"(~{prefill_p99:.1f}ms after the queue) — look at "
                    "prefill bucketing / chunked-prefill interleave"
                )
    else:
        lines.append("SLO: no completed requests in the reservoir yet")

    if not polls:
        lines.append("no poll records (no traffic since the ring opened)")
        if sheds:
            lines.append(f"{len(sheds)} shed events recorded")
        lines.extend(_swap_lines(swaps))
        lines.extend(_migration_lines(
            drains, ck_exports, migrated, swap_preempts
        ))
        # a prefill-role pool member never polls: its whole story is the
        # export stream
        lines.extend(_kv_lines(kv_exports, kv_inserts))
        lines.extend(_tier_lines(
            kv_demotes, kv_promotes, tier_hits, dump.get("kv_tier") or {}
        ))
        lines.extend(_pager_lines(
            page_ins, page_outs, tenant_switches,
            dump.get("weight_pager") or {},
            dump.get("tenant_scheduler") or {},
        ))
        lines.extend(_fault_lines(restarts, ejects, readmits, degraded))
        lines.extend(_pressure_lines(
            preempts, resumes, reclaims, budgets, dump.get("pressure") or {}
        ))
        lines.extend(_device_time_lines(
            polls, dump.get("profiler") or {}, dump.get("slo_burn") or {}
        ))
        lines.extend(_planner_lines(planner_retunes))
        return lines

    # -- batch composition --------------------------------------------------
    avg_active = sum(p.get("active", 0) for p in polls) / len(polls)
    avg_queue = sum(p.get("queue", 0) for p in polls) / len(polls)
    admits = sum(p.get("admitted", 0) for p in polls)
    lines.append(
        f"{len(polls)} working polls: avg {avg_active:.1f} active lanes, "
        f"avg admit-queue depth {avg_queue:.1f}, {admits} admissions"
    )

    # -- depth-group plan + cost-model verdicts ------------------------------
    planned = [p for p in polls if "plan" in p]
    # fused polls carry the same groups/distinct_buckets/merged fields —
    # the cost-model verdict must not go dark when fused decode is on
    decode = [p for p in planned if p["plan"].get("mode") in ("decode", "fused")]
    if decode:
        split = [p for p in decode if len(p["plan"].get("groups", [])) > 1]
        merged_polls = [p for p in decode if p["plan"].get("merged", 0) > 0]
        mixed = [p for p in decode if p["plan"].get("distinct_buckets", 1) > 1]
        lines.append(
            f"depth grouping: {len(mixed)}/{len(decode)} decode polls had "
            f"mixed attention depths; {_pct(len(split), len(decode)):.0f}% "
            f"dispatched split sub-bursts, cost model merged groups on "
            f"{_pct(len(merged_polls), len(decode)):.0f}% of polls"
        )
        if mixed and not split:
            lines.append(
                "DIAGNOSIS: depths mix but every poll merged — either "
                "depth_groups is off/1 or the cost model says splits don't "
                "pay at this model size (see depth_group_split_bytes)"
            )
    spec = [p for p in planned if p["plan"].get("mode") == "spec"]
    if spec:
        lines.append(f"speculative decode: {len(spec)} spec-burst polls")

    # -- fused multi-step decode ---------------------------------------------
    fused = [p for p in planned if p["plan"].get("mode") == "fused"]
    if fused:
        ks = [p["plan"].get("k", 1) for p in fused]
        k_max = max(p["plan"].get("k_max", 1) for p in fused)
        reasons: Dict[str, int] = {}
        for p in fused:
            r = p["plan"].get("shrunk_by")
            if r:
                reasons[r] = reasons.get(r, 0) + 1
        reason_txt = (
            "; shrunk by " + ", ".join(
                f"{n}x {r}" for r, n in sorted(reasons.items())
            )
            if reasons else ""
        )
        lines.append(
            f"fused decode: {len(fused)} fused polls, realized K avg "
            f"{sum(ks) / len(ks):.1f} / min {min(ks)} "
            f"(configured {k_max}){reason_txt}"
        )
        # collapse = realized K pinned at its observed floor, well below
        # the configured max. _fused_plan never shrinks below
        # min(steps_per_poll, k_max), so "k <= 1" would be dead code for
        # any steps_per_poll > 1 — compare against the floor the run
        # actually hit instead.
        floor = min(ks)
        collapsed = [k for k in ks if k <= floor]
        if floor < k_max and len(collapsed) >= max(4, len(ks) // 2):
            lines.append(
                f"DIAGNOSIS: K collapsed to {floor} (configured {k_max}) "
                f"on {_pct(len(collapsed), len(ks)):.0f}% of fused polls "
                f"— each dispatch carries only {floor} step(s), giving "
                "back most of the fused dispatch-floor win; look at the "
                "shrink reasons above (persistent `pressure` means the "
                "HBM ledger is latched — see "
                "seldon_engine_pressure_active; persistent `stop_budget` "
                "means short budgets dominate traffic)"
            )

    # -- chunked prefill interleave ------------------------------------------
    chunk_polls = [p for p in polls if p.get("prefill_chunks")]
    if chunk_polls:
        n_chunks = sum(p["prefill_chunks"] for p in chunk_polls)
        lines.append(
            f"chunked prefill: {n_chunks} chunks interleaved across "
            f"{len(chunk_polls)} polls "
            f"({_pct(len(chunk_polls), len(polls)):.0f}% of polls carried a "
            "chunk between decode bursts)"
        )

    # -- live weight swaps ----------------------------------------------------
    lines.extend(_swap_lines(swaps))

    # -- live migration (graceful drain, checkpoint handoff, resumes) --------
    lines.extend(_migration_lines(
        drains, ck_exports, migrated, swap_preempts
    ))

    # -- disaggregated serving (KV-slab handoff) ------------------------------
    lines.extend(_kv_lines(kv_exports, kv_inserts))

    # -- tiered KV memory (host-RAM spill tier) -------------------------------
    lines.extend(_tier_lines(
        kv_demotes, kv_promotes, tier_hits, dump.get("kv_tier") or {}
    ))

    # -- multi-tenant weight paging -------------------------------------------
    lines.extend(_pager_lines(
        page_ins, page_outs, tenant_switches,
        dump.get("weight_pager") or {},
        dump.get("tenant_scheduler") or {},
    ))

    # -- fault tolerance (supervision, peer failover, degradation) -----------
    lines.extend(_fault_lines(restarts, ejects, readmits, degraded))

    # -- HBM pressure (ledger, reclaim ladder, preemption) -------------------
    lines.extend(_pressure_lines(
        preempts, resumes, reclaims, budgets, dump.get("pressure") or {}
    ))

    # -- device-time ledger + SLO burn ----------------------------------------
    lines.extend(_device_time_lines(
        polls, dump.get("profiler") or {}, dump.get("slo_burn") or {}
    ))

    # -- autonomic planner retunes --------------------------------------------
    lines.extend(_planner_lines(planner_retunes))

    # -- prefix cache ---------------------------------------------------------
    hits = sum(p.get("prefix_hits", 0) for p in polls)
    evicted = sum(p.get("prefix_evicted", 0) for p in polls)
    if hits or evicted:
        lines.append(
            f"prefix cache: {hits} admit hits, {evicted} radix evictions "
            "inside the recorded window"
        )

    # -- shed -----------------------------------------------------------------
    if sheds:
        reasons: Dict[str, int] = {}
        for s in sheds:
            reasons[s.get("reason", "?")] = reasons.get(s.get("reason", "?"), 0) + 1
        lines.append(
            "load shedding: "
            + ", ".join(f"{n}x {r}" for r, n in sorted(reasons.items()))
        )
        lines.append(
            "DIAGNOSIS: requests were shed before work — clients saw 429s; "
            "queue depth above exceeds what the observed completion rate "
            "can drain"
        )
    return lines


def report(payload: Dict[str, Any]) -> Dict[str, Dict[str, List[str]]]:
    """Per-unit structured report: every narrative line plus the
    DIAGNOSIS subset broken out (dashboards key alerts off it)."""
    units = payload.get("units")
    if units is None:
        units = {"(batcher)": payload}
    out: Dict[str, Dict[str, List[str]]] = {}
    for name, dump in units.items():
        lines = diagnose(dump)
        out[name] = {
            "lines": lines,
            "diagnosis": [l for l in lines if l.startswith("DIAGNOSIS")],
        }
    return out


def render(payload: Dict[str, Any]) -> str:
    out: List[str] = []
    for name, unit in report(payload).items():
        out.append(f"=== flight report: {name} ===")
        out.extend("  " + line for line in unit["lines"])
    return "\n".join(out)


def main(argv: List[str]) -> int:
    args = [a for a in argv[1:] if a != "--json"]
    as_json = "--json" in argv[1:]
    if len(args) != 1 or args[0] in ("-h", "--help"):
        print(__doc__, file=sys.stderr)
        return 2
    raw = sys.stdin.read() if args[0] == "-" else open(args[0]).read()
    payload = json.loads(raw)
    if as_json:
        print(json.dumps(report(payload), indent=2, sort_keys=True))
    else:
        print(render(payload))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
