#!/usr/bin/env python3
"""CI smoke for pod-scale sharded generate serving.

Forces an 8-device host-platform mesh (the CPU stand-in for a pod
slice), boots one tiny checkpoint twice behind real engines on
sockets — an unmeshed 1-device server and a ``mesh_shape`` server with
mesh-sharded params + sharded KV cache — then asserts:

* greedy AND seeded-sampling responses through the sharded engine are
  byte-identical to the 1-device server's (serving math is
  sharded-storage / replicated-compute, so the mesh must never change
  an output byte), across plain decode, a shared-prefix repeat and a
  chunked long-prompt admission;
* the ``seldon.io/mesh`` annotation round-trips through a predictor
  spec into the same mesh the knob builds, and a malformed shape is
  refused at admission;
* the ``seldon_engine_mesh_*`` series (devices / data / model /
  param_shard_bytes / kv_shard) are present in the Prometheus
  exposition with the right values, and the unmeshed engine publishes
  none of them.

Run directly (``JAX_PLATFORMS=cpu python tools/sharded_smoke.py``) or
from the CI sharded_smoke step. Exits non-zero on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the pod-slice stand-in: 8 host devices, set BEFORE jax imports
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    # runtime thread-role assertions (analysis/roles.py) fail the smoke
    # loudly on a scheduler-thread violation (must precede seldon imports)
    os.environ.setdefault("SELDON_DEBUG_THREADS", "1")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.graph.engine_metrics import REGISTRY
    from seldon_core_tpu.graph.spec import GraphSpecError, PredictorSpec
    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.parallel.mesh import MeshShapeError
    from seldon_core_tpu.servers.generateserver import GenerateServer

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    mesh_shape = "data=2,model=4"
    with tempfile.TemporaryDirectory(prefix="sharded-smoke-") as root:
        cfg = {"vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 4,
               "n_kv_heads": 4, "d_ff": 64, "max_seq": 64}
        model_dir = write_model_dir(root, "llm", cfg)
        common = dict(model_uri=model_dir, slots=2, steps_per_poll=2,
                      warmup_prompt_lens=[4], warmup_max_new_tokens=8,
                      prefix_cache_hbm_bytes=1 << 20,
                      prefix_cache_min_tokens=8)

        plain = GenerateServer(**common)
        plain.load()
        shard = GenerateServer(mesh_shape=mesh_shape, prefill_chunk=8,
                               **common)
        shard.load()

        plain_h = EngineHarness(plain, name="plain").start()
        shard_h = EngineHarness(shard, name="sharded").start()
        headers = {"Content-Type": "application/json"}

        def gen(port: int, prompt, temperature=0.0, seed=0) -> dict:
            conn = http.client.HTTPConnection("127.0.0.1", port)
            conn.request("POST", "/api/v0.1/predictions", json.dumps({
                "jsonData": {"prompt_tokens": [prompt], "max_new_tokens": 8,
                             "temperature": temperature, "seed": seed},
            }).encode(), headers)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload[:160]!r}")
            return json.loads(payload)["jsonData"]

        try:
            # -- the mesh the knob built ----------------------------------
            mesh = shard.batcher.mesh
            check("sharded server serves on the requested mesh",
                  mesh is not None and dict(mesh.shape) ==
                  {"data": 2, "model": 4},
                  f"mesh={None if mesh is None else dict(mesh.shape)}")

            # -- byte identity: 1-device vs 8-device mesh -----------------
            prompts = [[5, 6, 7, 8], [9, 10, 11], [1, 2, 3, 4, 5, 6]]
            for p in prompts:
                ref = gen(plain_h.http_port, p)["tokens"][0]
                got = gen(shard_h.http_port, p)["tokens"][0]
                check(f"greedy identical (len {len(p)})", got == ref,
                      "" if got == ref else f"{got} != {ref}")
            for i, p in enumerate(prompts):
                ref = gen(plain_h.http_port, p, 0.8, 17 + i)["tokens"][0]
                got = gen(shard_h.http_port, p, 0.8, 17 + i)["tokens"][0]
                check(f"seeded identical (len {len(p)})", got == ref,
                      "" if got == ref else f"{got} != {ref}")

            # shared-prefix repeat: the second admission splices the radix
            # prefix into the SHARDED cache and must not change a byte
            system = list(range(20, 32))
            _ = gen(shard_h.http_port, system + [40, 41])
            ref = gen(plain_h.http_port, system + [50, 51])["tokens"][0]
            got = gen(shard_h.http_port, system + [50, 51])
            check("shared-prefix greedy identical", got["tokens"][0] == ref)
            check("prefix splice actually hit",
                  (got.get("cache_hit_tokens") or [0])[0] >= 8,
                  f"hits={(got.get('cache_hit_tokens') or [0])[0]}")

            # chunked long-prompt admission through the sharded staging slab
            long_p = [(i * 7 + 3) % 61 for i in range(30)]
            ref = gen(plain_h.http_port, long_p)["tokens"][0]
            got = gen(shard_h.http_port, long_p)["tokens"][0]
            check("chunked-prefill greedy identical", got == ref,
                  "" if got == ref else f"{got} != {ref}")

            # -- seldon.io/mesh annotation: round-trip + refusal ----------
            from seldon_core_tpu.graph.spec import parse_mesh_annotation

            spec = PredictorSpec.from_dict({
                "name": "p", "graph": {"name": "m", "type": "MODEL",
                                       "implementation": "GENERATE_SERVER"},
                "annotations": {"seldon.io/mesh": mesh_shape},
            })
            check("seldon.io/mesh annotation parses to the knob's shape",
                  parse_mesh_annotation(spec) == {"data": 2, "model": 4})
            try:
                parse_mesh_annotation(PredictorSpec.from_dict({
                    "name": "p", "graph": {
                        "name": "m", "type": "MODEL",
                        "implementation": "GENERATE_SERVER"},
                    "annotations": {"seldon.io/mesh": "data=2,model=nope"},
                }))
                check("malformed seldon.io/mesh refused", False)
            except (GraphSpecError, MeshShapeError):
                check("malformed seldon.io/mesh refused", True)

            # -- the seldon_engine_mesh_* exposition ----------------------
            expo = REGISTRY.expose()
            for series in ("seldon_engine_mesh_devices",
                           "seldon_engine_mesh_data",
                           "seldon_engine_mesh_model",
                           "seldon_engine_mesh_param_shard_bytes",
                           "seldon_engine_mesh_kv_shard"):
                check(f"exposition has {series}", series in expo)
            gauges = {d["key"]: d["value"] for d in shard.metrics()}
            check("mesh gauges carry the served shape",
                  gauges.get("gen_mesh_devices") == 8
                  and gauges.get("gen_mesh_data") == 2
                  and gauges.get("gen_mesh_model") == 4
                  and gauges.get("gen_mesh_kv_shard") == 4,
                  f"gauges={ {k: v for k, v in gauges.items() if 'mesh' in k} }")
            check("per-shard param bytes strictly under the full residency",
                  0 < gauges.get("gen_mesh_param_shard_bytes", 0)
                  < shard._model.n_params() * 4)
            plain_gauges = {d["key"] for d in plain.metrics()}
            check("unmeshed engine publishes no mesh gauges",
                  not any(k.startswith("gen_mesh_") for k in plain_gauges))
        finally:
            plain_h.stop()
            shard_h.stop()
            plain.close()
            shard.close()

    if failures:
        print(f"\nsharded smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\nsharded smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
