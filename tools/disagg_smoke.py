#!/usr/bin/env python3
"""CI smoke for disaggregated prefill/decode serving.

Boots a tiny checkpoint three ways behind real engines on sockets — a
unified server, a prefill-pool server exporting KV over BOTH the
loopback and the chunked TCP transport, and a decode-pool server per
transport — then asserts:

* greedy responses through the disaggregated engines (loopback AND TCP)
  are byte-identical to the unified server's;
* a shared-prefix repeat through the prefix-cache-enabled decode pool
  reports ``cache_hit_tokens`` (the transfer-dedup accounting) and
  bumps ``kv_transfer_bytes_saved``;
* the ``seldon_engine_kv_transfer_*`` series are present in the
  Prometheus exposition with export/import directions;
* peer death mid-run: with the prefill listener killed, the decode
  pool ejects the peer (``peer_ejected`` flight record) and keeps
  serving byte-identical greedy output via failover/local degradation.

Run directly (``JAX_PLATFORMS=cpu python tools/disagg_smoke.py``) or
from the CI disaggregation step. Exits non-zero on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runtime thread-role assertions (analysis/roles.py): remote-admit /
    # failover paths run on worker threads — a scheduler-thread violation
    # fails the smoke loudly (must precede seldon imports)
    os.environ.setdefault("SELDON_DEBUG_THREADS", "1")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import http.client

    from seldon_core_tpu.graph.engine_metrics import REGISTRY
    from seldon_core_tpu.modelbench import EngineHarness, write_model_dir
    from seldon_core_tpu.serving.disagg import PrefillTransportServer
    from seldon_core_tpu.servers.generateserver import GenerateServer

    failures = []

    def check(name: str, ok: bool, detail: str = ""):
        print(f"{'ok  ' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory(prefix="disagg-smoke-") as root:
        cfg = {"vocab_size": 256, "d_model": 32, "n_layers": 2, "n_heads": 2,
               "n_kv_heads": 2, "d_ff": 64, "max_seq": 64}
        model_dir = write_model_dir(root, "llm", cfg)
        common = dict(model_uri=model_dir, steps_per_poll=4,
                      warmup_prompt_lens=[4], warmup_max_new_tokens=6,
                      prefix_cache_hbm_bytes=1 << 20,
                      prefix_cache_min_tokens=8)

        unified = GenerateServer(slots=2, **common)
        unified.load()
        prefill = GenerateServer(role="prefill", **{
            **common, "prefix_cache_hbm_bytes": 0,
        })
        prefill.load()
        kv_listener = PrefillTransportServer(prefill, port=0)
        dec_lo = GenerateServer(slots=2, role="decode", **common)
        dec_lo.load()
        dec_lo.set_peer(prefill)
        dec_tcp = GenerateServer(
            slots=2, role="decode", peer=f"127.0.0.1:{kv_listener.port}",
            **common,
        )
        dec_tcp.load()

        uni_h = EngineHarness(unified, name="unified").start()
        lo_h = EngineHarness(dec_lo, name="disagg-loopback").start()
        tcp_h = EngineHarness(dec_tcp, name="disagg-tcp").start()
        headers = {"Content-Type": "application/json"}

        def greedy(port: int, prompt) -> dict:
            conn = http.client.HTTPConnection("127.0.0.1", port)
            conn.request("POST", "/api/v0.1/predictions", json.dumps({
                "jsonData": {"prompt_tokens": [prompt], "max_new_tokens": 6,
                             "temperature": 0.0},
            }).encode(), headers)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload[:160]!r}")
            return json.loads(payload)["jsonData"]

        try:
            # -- byte identity: unified vs loopback vs TCP ----------------
            prompts = [[5, 6, 7, 8], [9, 10, 11], [1, 2, 3, 4, 5, 6]]
            for p in prompts:
                ref = greedy(uni_h.http_port, p)["tokens"][0]
                lo = greedy(lo_h.http_port, p)["tokens"][0]
                tcp = greedy(tcp_h.http_port, p)["tokens"][0]
                check(f"loopback greedy identical (len {len(p)})", lo == ref,
                      "" if lo == ref else f"{lo} != {ref}")
                check(f"tcp greedy identical (len {len(p)})", tcp == ref,
                      "" if tcp == ref else f"{tcp} != {ref}")

            # -- shared-prefix transfer dedup -----------------------------
            system = list(range(20, 32))  # 12-token shared system prompt
            first = greedy(lo_h.http_port, system + [40, 41])
            ref2 = greedy(uni_h.http_port, system + [50, 51])["tokens"][0]
            second = greedy(lo_h.http_port, system + [50, 51])
            check("shared-prefix greedy identical",
                  second["tokens"][0] == ref2)
            hits = (second.get("cache_hit_tokens") or [0])[0]
            check("decode side reports cache_hit_tokens on remote admit",
                  hits >= 8, f"hits={hits}")
            saved = dec_lo.batcher.stats["kv_transfer_bytes_saved"]
            check("kv_transfer_bytes_saved > 0", saved > 0, f"saved={saved}")

            # -- the seldon_engine_kv_transfer_* exposition ---------------
            expo = REGISTRY.expose()
            for series in ("seldon_engine_kv_transfer_slabs",
                           "seldon_engine_kv_transfer_bytes",
                           "seldon_engine_kv_transfer_bytes_saved"):
                check(f"exposition has {series}", series in expo)
            check("import direction labeled",
                  'direction="import"' in expo)
            check("import slab counter counts the transfers",
                  REGISTRY.counter_total(
                      "seldon_engine_kv_transfer_slabs",
                      {"direction": "import"},
                  ) >= len(prompts) * 2 + 2)
            check("bytes_saved series counts the dedup",
                  REGISTRY.counter_total(
                      "seldon_engine_kv_transfer_bytes_saved", {},
                  ) > 0)
            _ = first  # first shared request seeds the radix cache

            # -- tiered KV memory: peer prefix pull over TCP --------------
            # a prefill member with the host tier on publishes every
            # exported slab; a FRESH decode member (empty local radix)
            # pulls the shared prefix from the PEER'S tier over TCP,
            # promotes it locally, and ships the suffix only — the
            # kv_transfer_bytes_saved accounting must stay correct
            # (decode-side count, covered tokens priced per slab token)
            tier_common = dict(common, host_kv_tier_bytes=64 << 20,
                               kv_tier_min_tokens=8)
            pf_tier = GenerateServer(role="prefill", **tier_common)
            pf_tier.load()
            tier_listener = PrefillTransportServer(pf_tier, port=0)
            dec_tier = GenerateServer(
                slots=2, role="decode",
                peer=f"127.0.0.1:{tier_listener.port}", **tier_common,
            )
            dec_tier.load()
            tier_h = EngineHarness(dec_tier, name="disagg-kvtier").start()
            try:
                shared = list(range(40, 52))  # 12-token shared prefix
                ref_t = greedy(uni_h.http_port, shared + [60, 61])["tokens"][0]
                # seed the PREFILL tier: one export publishes the slab
                pf_tier.batcher.export_prefill(shared + [55, 56],
                                               max_new_tokens=6)
                check("prefill tier holds the exported prefix",
                      pf_tier.batcher.kv_tier_summary()["prefix_entries"]
                      >= 1)
                saved0 = dec_tier.batcher.stats["kv_transfer_bytes_saved"]
                out_t = greedy(tier_h.http_port, shared + [60, 61])
                check("peer tier pull greedy identical",
                      out_t["tokens"][0] == ref_t)
                pulled = (out_t.get("cache_hit_tokens") or [0])[0]
                check("peer tier pull covered the shared prefix",
                      pulled >= 8, f"covered={pulled}")
                check("decode member promoted the peer slab",
                      dec_tier.batcher.stats["kv_tier_promotions"] >= 1)
                saved = (dec_tier.batcher.stats["kv_transfer_bytes_saved"]
                         - saved0)
                want_saved = pulled * dec_tier.batcher._slab_token_bytes
                check("bytes_saved accounting matches covered tokens",
                      saved == want_saved,
                      f"saved={saved} want={want_saved}")
                pf_tier.batcher.sync_kv_tier_stats()
                check("prefill tier counted the peer hit",
                      pf_tier.batcher.stats["kv_tier_hits"] >= 1)
                expo = REGISTRY.expose()
                check("exposition has seldon_engine_kv_tier_promotions",
                      "seldon_engine_kv_tier_promotions" in expo)
            finally:
                tier_h.stop()
                tier_listener.close()
                pf_tier.close()
                dec_tier.close()

            # -- peer death mid-run: failover / local degradation ---------
            # kill the TCP listener, then keep issuing requests through
            # the decode engine: the dead peer is ejected (peer_ejected
            # flight record + counter) and every request still answers
            # byte-identical to unified — this pool's only peer is gone,
            # so service degrades to LOCAL unified prefill
            probe = [12, 13, 14, 15]
            ref3 = greedy(uni_h.http_port, probe)["tokens"][0]
            kv_listener.close()
            import time as _time

            _time.sleep(0.2)  # let the OS actually drop the listen port
            for i in range(3):
                got = greedy(tcp_h.http_port, probe)["tokens"][0]
                check(f"peer-death request {i} byte-identical", got == ref3,
                      "" if got == ref3 else f"{got} != {ref3}")
            st = dec_tcp.batcher.stats
            check("peer ejected after listener death",
                  st["peer_ejections"] >= 1,
                  f"ejections={st['peer_ejections']}")
            check("decode degraded to local prefill",
                  st["degraded_local_prefill"] >= 1,
                  f"degraded={st['degraded_local_prefill']}")
            eject_recs = [
                e for e in dec_tcp.batcher.flight.dump()["entries"]
                if e["type"] == "peer_ejected"
            ]
            check("peer_ejected flight record present", bool(eject_recs))
            check("peer-ejection series in exposition",
                  "seldon_engine_peer_ejections" in REGISTRY.expose())
        finally:
            uni_h.stop()
            lo_h.stop()
            tcp_h.stop()
            kv_listener.close()
            for c in (unified, prefill, dec_lo, dec_tcp):
                c.close()

    if failures:
        print(f"\ndisagg smoke FAILED: {failures}", file=sys.stderr)
        return 1
    print("\ndisagg smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
